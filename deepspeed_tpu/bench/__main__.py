"""``python -m deepspeed_tpu.bench`` — history maintenance subcommands.

* ``recover``  — re-ingest committed ``BENCH_r*.json`` round artifacts
  into ``bench_history/history.jsonl`` (skips rounds already recorded;
  this is how the r01–r05 trajectory was recovered after r03–r05 went
  ``"parsed": null``)
* ``validate`` — validate a bench result / history file against the
  versioned schema (exit 0 valid, 1 invalid, 2 error)
* ``history``  — print the recorded trajectory as a table

``bench-diff`` (round-to-round comparison) is its own console entry:
``deepspeed_tpu.bench.cli``.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from deepspeed_tpu.bench import history as history_mod
from deepspeed_tpu.bench import legacy
from deepspeed_tpu.bench.schema import validate_record, validate_result


def _cmd_recover(args) -> int:
    root = args.repo or history_mod.default_repo_root()
    records = legacy.recover_rounds(root)
    if not records:
        print(f"recover: no BENCH_r*.json under {root}", file=sys.stderr)
        return 1
    existing, _ = history_mod.load_history(args.history)
    seen = {rec.get("round") for rec in existing}
    wrote = 0
    for rec in records:
        if rec["round"] in seen and not args.force:
            print(f"recover: {rec['round']} already in history, skipped")
            continue
        bad = validate_record(rec)
        if bad:
            print(f"recover: {rec['round']} produced an invalid record: "
                  f"{bad[0]}", file=sys.stderr)
            return 2
        path = history_mod.append_record(rec, args.history)
        wrote += 1
        status = "complete" if rec["complete"] else "partial"
        how = "recovered from tail" if rec["recovered"] else "from parsed"
        n_entries = len(rec["result"].get("entries") or {})
        head = rec["result"].get("headline") or {}
        val = head.get("value")
        print(f"recover: {rec['round']} -> {path} [{status}, {how}; "
              f"headline={'%.1f' % val if isinstance(val, (int, float)) else 'lost'}, "
              f"{n_entries} entries]")
    print(f"recover: wrote {wrote} record(s)")
    return 0


def _cmd_validate(args) -> int:
    try:
        with open(args.file, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        print(f"validate: {e}", file=sys.stderr)
        return 2
    if args.file.endswith(".jsonl"):
        errs: List[str] = []
        for i, line in enumerate(text.splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                errs.append(f"line {i}: unparseable")
                continue
            errs += [f"line {i}: {e}" for e in validate_record(rec)]
    else:
        try:
            obj = json.loads(text)
        except ValueError:
            # a raw bench stdout log: validate its recovered final line
            obj, _ = legacy.recover_from_text(text)
        errs = (validate_record(obj)
                if isinstance(obj, dict) and "record_version" in obj
                else validate_result(obj))
    for e in errs:
        print(f"validate: {e}")
    print(f"validate: {'OK' if not errs else f'{len(errs)} error(s)'}")
    return 0 if not errs else 1


def _cmd_history(args) -> int:
    records, notes = history_mod.load_history(args.history)
    if not records:
        print("history: empty (run `python -m deepspeed_tpu.bench "
              "recover` to ingest committed rounds)")
        return 0
    print(f"{'round':8s} {'headline':>12s} {'mfu':>6s} {'vs_base':>8s} "
          f"{'entries':>7s}  status")
    for rec in records:
        result = rec.get("result") or {}
        head = result.get("headline") or {}
        val = head.get("value")
        mfu = head.get("mfu")
        vsb = head.get("vs_baseline")
        best = head.get("best_row") or {}
        status = ("complete" if rec.get("complete") else
                  "partial" if (result.get("entries") or head) else "lost")
        if rec.get("recovered"):
            status += ",recovered"
        if rec.get("rc") not in (0, None):
            status += f",rc={rec['rc']}"
        note = (f" best={best.get('name')}@mfu{best.get('mfu')}"
                if best.get("name") else "")
        print(f"{rec.get('round', '?'):8s} "
              f"{val if val is not None else '—':>12} "
              f"{mfu if mfu is not None else '—':>6} "
              f"{vsb if vsb is not None else '—':>8} "
              f"{len(result.get('entries') or {}):>7d}  {status}{note}")
    for note in notes:
        print(f"history: note: {note}", file=sys.stderr)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.bench",
        description="bench history maintenance (recover / validate / "
                    "history); see also the bench-diff CLI")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("recover",
                        help="ingest committed BENCH_r*.json into history")
    pr.add_argument("--repo", default=None,
                    help="checkout root (default: this package's parent)")
    pr.add_argument("--history", default=None,
                    help="history dir or .jsonl (default: bench_history/)")
    pr.add_argument("--force", action="store_true",
                    help="re-append rounds already in history")
    pv = sub.add_parser("validate",
                        help="validate a result/record/.jsonl file")
    pv.add_argument("file")
    ph = sub.add_parser("history", help="print the recorded trajectory")
    ph.add_argument("--history", default=None)
    args = p.parse_args(argv)
    return {"recover": _cmd_recover,
            "validate": _cmd_validate,
            "history": _cmd_history}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
