"""Collective bandwidth benchmark — the ``ds_bench`` analog.

Parity: reference ``bin/ds_bench`` → ``benchmarks/communication`` (sweeps
all_reduce/all_gather/... sizes, prints GB/s and busbw). Here the sweep runs
psum / all_gather / psum_scatter / all_to_all as jitted shard_map programs
over a mesh axis and reports algorithm bandwidth + bus bandwidth with the
standard ring-collective correction factors.

CLI: ``python -m deepspeed_tpu.utils.comm_bench [--axis data] [--trials 20]``
"""
from __future__ import annotations

import argparse
import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _timeit(fn, x, trials: int) -> float:
    fn(x).block_until_ready()  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / trials


def bench_collectives(mesh: Optional[Mesh] = None, axis: str = "data",
                      sizes_mb: Optional[List[float]] = None,
                      trials: int = 20) -> List[Dict]:
    """Returns rows: {op, size_bytes, time_s, algbw_gbps, busbw_gbps}."""
    from deepspeed_tpu.comm.mesh import get_mesh_manager

    mesh = mesh or get_mesh_manager().mesh
    world = mesh.shape.get(axis, 1)
    sizes_mb = sizes_mb or [1, 4, 16, 64]
    rows: List[Dict] = []

    def sm(fn, in_spec, out_spec):
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_spec,
                                 out_specs=out_spec, check_vma=False))

    for mb in sizes_mb:
        n = int(mb * 1e6 / 4)
        n = (n // (world * world)) * world * world or world * world
        x = jnp.ones((n,), jnp.float32)
        xs = jnp.ones((world, n // world), jnp.float32)
        bytes_ = n * 4

        ops = {
            # busbw factors per the NCCL-tests convention
            "all_reduce": (sm(lambda v: lax.psum(v, axis), P(axis, None), P(axis, None)),
                           xs, 2 * (world - 1) / world),
            "all_gather": (sm(lambda v: lax.all_gather(v, axis, tiled=True),
                              P(axis), P(None)),
                           x, (world - 1) / world),
            "reduce_scatter": (sm(lambda v: lax.psum_scatter(v, axis, tiled=True),
                                  P(None), P(axis)),
                               x, (world - 1) / world),
            "all_to_all": (sm(lambda v: lax.all_to_all(
                v.reshape(world, -1), axis, split_axis=0, concat_axis=0,
                tiled=True).reshape(1, -1),
                P(axis, None), P(axis, None)),
                xs, (world - 1) / world),
        }
        for name, (fn, arg, factor) in ops.items():
            t = _timeit(fn, arg, trials)
            algbw = bytes_ / t / 1e9
            rows.append({
                "op": name, "size_bytes": bytes_, "time_s": t,
                "algbw_gbps": algbw, "busbw_gbps": algbw * factor,
            })
    return rows


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--axis", default="data")
    p.add_argument("--trials", type=int, default=20)
    p.add_argument("--sizes-mb", type=float, nargs="*", default=None)
    args = p.parse_args()

    from deepspeed_tpu.comm.mesh import MeshConfig, get_mesh_manager, initialize_mesh

    try:
        mesh = get_mesh_manager().mesh
    except Exception:
        mesh = initialize_mesh(MeshConfig()).mesh
    rows = bench_collectives(mesh, args.axis, args.sizes_mb, args.trials)
    print(f"{'op':<16}{'size':>12}{'time':>12}{'algbw GB/s':>14}{'busbw GB/s':>14}")
    for r in rows:
        print(f"{r['op']:<16}{r['size_bytes']:>12}{r['time_s'] * 1e3:>10.2f}ms"
              f"{r['algbw_gbps']:>14.2f}{r['busbw_gbps']:>14.2f}")


if __name__ == "__main__":
    main()
