"""Collective bandwidth benchmark — the ``ds_bench`` analog.

Parity: reference ``bin/ds_bench`` → ``benchmarks/communication`` (sweeps
all_reduce/all_gather/... sizes, prints GB/s and busbw). Here the sweep runs
psum / all_gather / psum_scatter / all_to_all as jitted shard_map programs
over a mesh axis and reports algorithm bandwidth + bus bandwidth with the
standard ring-collective correction factors.

CLI: ``python -m deepspeed_tpu.utils.comm_bench [--axis data] [--trials 20]``
"""
from __future__ import annotations

import argparse
import time
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _timeit(fn, x, trials: int) -> float:
    fn(x).block_until_ready()  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(trials):
        out = fn(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / trials


def bench_collectives(mesh: Optional[Mesh] = None, axis: str = "data",
                      sizes_mb: Optional[List[float]] = None,
                      trials: int = 20) -> List[Dict]:
    """Returns rows: {op, size_bytes, time_s, algbw_gbps, busbw_gbps}."""
    from deepspeed_tpu.comm.mesh import get_mesh_manager

    mesh = mesh or get_mesh_manager().mesh
    world = mesh.shape.get(axis, 1)
    sizes_mb = sizes_mb or [1, 4, 16, 64]
    rows: List[Dict] = []

    def sm(fn, in_spec, out_spec):
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_spec,
                                 out_specs=out_spec, check_vma=False))

    # busbw correction factors come from the ONE shared table
    # (comm/bandwidth.py) — the same convention calc_bw_log and the
    # compiled-collective ledger report
    from deepspeed_tpu.comm.bandwidth import busbw_factor

    for mb in sizes_mb:
        n = int(mb * 1e6 / 4)
        n = (n // (world * world)) * world * world or world * world
        x = jnp.ones((n,), jnp.float32)
        xs = jnp.ones((world, n // world), jnp.float32)
        bytes_ = n * 4

        ops = {
            "all_reduce": (sm(lambda v: lax.psum(v, axis), P(axis, None), P(axis, None)),
                           xs),
            "all_gather": (sm(lambda v: lax.all_gather(v, axis, tiled=True),
                              P(axis), P(None)),
                           x),
            "reduce_scatter": (sm(lambda v: lax.psum_scatter(v, axis, tiled=True),
                                  P(None), P(axis)),
                               x),
            "all_to_all": (sm(lambda v: lax.all_to_all(
                v.reshape(world, -1), axis, split_axis=0, concat_axis=0,
                tiled=True).reshape(1, -1),
                P(axis, None), P(axis, None)),
                xs),
        }
        for name, (fn, arg) in ops.items():
            t = _timeit(fn, arg, trials)
            algbw = bytes_ / t / 1e9
            rows.append({
                "op": name, "size_bytes": bytes_, "time_s": t,
                "algbw_gbps": algbw,
                "busbw_gbps": algbw * busbw_factor(name, world),
            })
    return rows


def bench_compressed_wire(mesh: Optional[Mesh] = None, axis: str = "data",
                          size_mb: float = 16, trials: int = 5,
                          block: int = 256) -> List[Dict]:
    """Wire-volume + fidelity comparison of the compressed gradient
    collectives against the exact ones (reference rationale: qgZ exists
    purely to cut wire bytes — ``runtime/comm/coalesced_collectives.py``).

    Rows: exact fp32 allreduce, qgZ int8 reduce-scatter wire
    (``parallel/compressed._q_reduce_scatter`` — all_to_all of int8 blocks +
    fp32 block scales), and the 1-bit packed-sign allreduce
    (``ops/quantization.packed_sign_allreduce`` — N/8 sign bytes + scales).
    ``wire_bytes_per_rank`` counts the bytes each rank actually hands the
    collective (payload dtype × shape — analytic, same convention for all
    three); ``rel_err`` is vs the exact fp32 sum of the same per-rank
    contributions."""
    from deepspeed_tpu.comm.bandwidth import busbw_factor
    from deepspeed_tpu.comm.mesh import get_mesh_manager
    from deepspeed_tpu.ops.quantization import packed_sign_allreduce
    from deepspeed_tpu.parallel.compressed import _q_reduce_scatter

    mesh = mesh or get_mesh_manager().mesh
    world = mesh.shape.get(axis, 1)
    n = int(size_mb * 1e6 / 4)
    n = (n // (world * block)) * world * block or world * block
    rng = np.random.default_rng(0)
    # per-rank gradient-like contributions (heavy-tailed enough that int8
    # block quantization has real work to do)
    contrib = jnp.asarray(rng.standard_normal((world, n)) *
                          rng.gamma(1.0, 1.0, (world, 1)), jnp.float32)
    exact_sum = np.asarray(jnp.sum(contrib, axis=0))
    exact_l2 = float(np.linalg.norm(exact_sum))

    def sm(fn, in_spec, out_spec):
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_spec,
                                 out_specs=out_spec, check_vma=False))

    rows: List[Dict] = []

    # 1) exact fp32 allreduce (psum) — the referent
    f_exact = sm(lambda v: lax.psum(v, axis), P(axis, None), P(axis, None))
    t = _timeit(f_exact, contrib, trials)
    rows.append({"op": "allreduce_exact_fp32", "size_bytes": n * 4,
                 "wire_bytes_per_rank": n * 4, "wire_reduction": 1.0,
                 "time_s": t, "rel_err": 0.0,
                 "logical_busbw_gbps":
                     n * 4 * busbw_factor("all_reduce", world) / t / 1e9})

    # 2) qgZ int8 wire: all_to_all moves int8 payload + per-block fp32
    #    scales. Each rank holds its per-rank contribution row [n] (in the
    #    engine these are the local grads, reshaped to per-destination rows)
    def qgz_local(v):
        g = v[0].reshape(world, -1)           # destination-major rows
        return _q_reduce_scatter(g, axis, world, block)[None]

    f_q = sm(qgz_local, P(axis, None), P(axis, None))
    t = _timeit(f_q, contrib, trials)
    # each rank's reduced shard, concatenated == exact sum
    got = np.asarray(jax.device_get(f_q(contrib))).reshape(-1)
    err_q = float(np.linalg.norm(got - exact_sum) / exact_l2)
    wire_q = n + 4 * (n // block)                      # int8 + fp32 scales
    rows.append({"op": "reduce_scatter_qgz_int8", "size_bytes": n * 4,
                 "wire_bytes_per_rank": wire_q,
                 "wire_reduction": round(n * 4 / wire_q, 2),
                 "time_s": t, "rel_err": err_q,
                 "logical_busbw_gbps":
                     n * 4 * busbw_factor("reduce_scatter", world) / t / 1e9})

    # 3) 1-bit packed-sign allreduce (error feedback zeroed: single-shot
    #    fidelity — training carries the error across steps)
    def onebit(v):
        red, _ = packed_sign_allreduce(v[0], jnp.zeros_like(v[0]), axis,
                                       world, block)
        return red[None]

    f_1 = sm(onebit, P(axis, None), P(None, None))
    t = _timeit(f_1, contrib, trials)
    got1 = np.asarray(jax.device_get(f_1(contrib)))[0] * world   # mean→sum
    err_1 = float(np.linalg.norm(got1 - exact_sum) / exact_l2)
    wire_1 = n // 8 + 4 * (n // block)                # sign bits + scales
    rows.append({"op": "allreduce_onebit_sign", "size_bytes": n * 4,
                 "wire_bytes_per_rank": wire_1,
                 "wire_reduction": round(n * 4 / wire_1, 2),
                 "time_s": t, "rel_err": err_1,
                 "note": "single-shot sign-compression error (direction "
                         "preserved); training accuracy comes from the "
                         "per-step error feedback, not per-call fidelity "
                         "(1-bit Adam loss-parity tests)",
                 "logical_busbw_gbps":
                     n * 4 * busbw_factor("all_reduce", world) / t / 1e9})
    return rows


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--axis", default="data")
    p.add_argument("--trials", type=int, default=20)
    p.add_argument("--sizes-mb", type=float, nargs="*", default=None)
    args = p.parse_args()

    from deepspeed_tpu.comm.mesh import get_mesh_manager

    # lazily initializes a default mesh when none is configured
    mesh = get_mesh_manager().mesh
    rows = bench_collectives(mesh, args.axis, args.sizes_mb, args.trials)
    print(f"{'op':<16}{'size':>12}{'time':>12}{'algbw GB/s':>14}{'busbw GB/s':>14}")
    for r in rows:
        print(f"{r['op']:<16}{r['size_bytes']:>12}{r['time_s'] * 1e3:>10.2f}ms"
              f"{r['algbw_gbps']:>14.2f}{r['busbw_gbps']:>14.2f}")


if __name__ == "__main__":
    main()
