"""Wall-clock timers with device-synchronization fences.

Parity: reference ``deepspeed/utils/timer.py`` (``SynchronizedWallClockTimer``,
``ThroughputTimer``). On TPU there are no user-visible streams/events, so
synchronization is a ``jax.block_until_ready`` fence on a trivial device value
(``accelerator.synchronize``) before reading the host clock — the
``is_synchronized_device`` escape hatch the reference keeps for exactly this
class of device (``accelerator/abstract_accelerator.py:19``).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist, logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _sync() -> None:
    from deepspeed_tpu.accelerator import get_accelerator

    get_accelerator().synchronize()


def _phase_hist():
    """Telemetry feed: every fenced timer stop lands in the unified
    registry as ``train_phase_seconds{phase=<timer name>}`` — the fwd/bwd/
    step breakdown becomes scrapeable instead of log-only. Looked up fresh
    per stop (locked dict get; timers only run under wall_clock_breakdown)
    so registry resets can't strand a cached handle."""
    from deepspeed_tpu import telemetry

    return telemetry.histogram(
        "train_phase_seconds",
        "fenced wall time of named engine phases (fwd/bwd/step/"
        "train_batch timers)")


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start_time = 0.0
        self._elapsed = 0.0
        self._record: List[float] = []

    def start(self, sync: bool = True) -> None:
        if self.started:
            return
        if sync:
            _sync()
        self._start_time = time.perf_counter()
        self.started = True

    def stop(self, record: bool = True, sync: bool = True) -> None:
        if not self.started:
            return
        if sync:
            _sync()
        delta = time.perf_counter() - self._start_time
        self._elapsed += delta
        if record:
            self._record.append(delta)
        self.started = False
        try:
            _phase_hist().observe(delta, phase=self.name)
        except Exception as e:   # telemetry must never break a timer
            logger.debug(f"phase-histogram observe failed "
                         f"({type(e).__name__}: {e})")

    def reset(self) -> None:
        self.started = False
        self._elapsed = 0.0

    def elapsed(self, reset: bool = True) -> float:
        out = self._elapsed
        if self.started:
            out += time.perf_counter() - self._start_time
        if reset:
            self._elapsed = 0.0
        return out

    def mean(self) -> float:
        if not self._record:
            return 0.0
        return sum(self._record) / len(self._record)


class SynchronizedWallClockTimer:
    """Named timer registry; each timer fences the device before reading the clock."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name: str) -> bool:
        return name in self.timers

    @staticmethod
    def memory_usage() -> str:
        from deepspeed_tpu.accelerator import get_accelerator

        stats = get_accelerator().memory_stats()
        ib = stats.get("bytes_in_use", 0)
        pk = stats.get("peak_bytes_in_use", 0)
        return f"mem: in_use={ib / 2**30:.2f}GB peak={pk / 2**30:.2f}GB"

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks=None) -> None:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {elapsed:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        if memory_breakdown:
            msg += " | " + self.memory_usage()
        log_dist(msg, ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        assert normalizer > 0.0
        return {
            n: self.timers[n].mean() * 1000.0 / normalizer
            for n in names
            if n in self.timers
        }


class ThroughputTimer:
    """Tracks samples/sec across steps (reference ``utils/timer.py`` analog).

    Unlike the reference (CUDA events are cheap), a device fence on TPU —
    especially through a remote-execution tunnel — costs a full host↔device
    round trip and serializes the dispatch pipeline. So this timer measures
    WINDOWS: it fences once per ``steps_per_output`` report boundary and
    divides the window wall time by the steps in it. Between boundaries a
    train step pays zero sync overhead; with ``steps_per_output=None`` it
    never fences at all. Aggregate throughput is identical (each window is
    fence-to-fence wall time).
    """

    def __init__(self, batch_size: int, start_step: int = 2, steps_per_output: Optional[int] = None,
                 monitor_memory: bool = False, logging_fn=None):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or (lambda m: log_dist(m, ranks=[0]))
        self.global_step_count = 0
        self.local_step_count = 0
        self.total_elapsed_time = 0.0   # fenced wall time since start_step
        self._counted_steps = 0         # steps covered by total_elapsed_time
        # optional (duration_s, steps) callback fired on every fenced window
        # close — the telemetry feed for throughput gauges (async dispatch
        # makes un-fenced per-step walls meaningless, see class docstring)
        self.window_hook = None
        self._window_start: Optional[float] = None
        self._window_steps = 0
        self.started = False

    def update_epoch_count(self) -> None:
        self.local_step_count = 0

    def _should_report(self, steps: int = 1) -> bool:
        """True when the last ``steps`` increment crossed a report boundary
        (a fused multi-step stop may jump OVER the exact multiple)."""
        spo = self.steps_per_output
        if not spo:
            return False
        return (self.global_step_count // spo) > \
            ((self.global_step_count - steps) // spo)

    def start(self) -> None:
        self.started = True
        if self._window_start is None and self.global_step_count >= self.start_step:
            _sync()  # one fence to open the measurement window
            self._window_start = time.perf_counter()
            self._window_steps = 0

    def stop(self, global_step: bool = True, report_speed: bool = True,
             steps: int = 1) -> None:
        """``steps`` > 1 credits one fused multi-step dispatch
        (engine.train_batches) with all the optimizer steps it ran."""
        if not self.started:
            return
        self.started = False
        self.local_step_count += steps
        if global_step:
            self.global_step_count += steps
        if self._window_start is None or not global_step:
            return
        self._window_steps += steps
        if self._should_report(steps):
            duration, steps = self._close_window()
            if report_speed and steps:
                self.logging(
                    f"step={self.global_step_count} "
                    f"samples/sec={self.avg_samples_per_sec():.2f} "
                    f"ms/step={duration / steps * 1000:.1f}")

    def _close_window(self):
        """Fence, accrue the open window, and start a new one."""
        _sync()
        duration = time.perf_counter() - self._window_start
        steps = self._window_steps
        self.total_elapsed_time += duration
        self._counted_steps += steps
        self._window_start = time.perf_counter()
        self._window_steps = 0
        if self.window_hook is not None and steps:
            try:
                self.window_hook(duration, steps)
            except Exception as e:   # telemetry must never break the timer
                logger.debug(f"throughput window_hook failed "
                             f"({type(e).__name__}: {e})")
        return duration, steps

    def avg_samples_per_sec(self) -> float:
        # close the in-flight window lazily so the query is accurate at any
        # step (one fence per query, none per step)
        if self._window_start is not None and self._window_steps:
            self._close_window()
        if self._counted_steps == 0 or self.total_elapsed_time == 0.0:
            return 0.0
        return self.batch_size / (self.total_elapsed_time / self._counted_steps)
