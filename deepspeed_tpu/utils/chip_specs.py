"""Chip datasheet facts shared by bench.py and the telemetry MFU gauge.

One table so the headline bench MFU and the scraped ``train_mfu`` gauge can
never disagree about a chip's peak. Stdlib-only — importable from the bench
orchestrator before jax loads.
"""
from __future__ import annotations

from typing import Optional

# bf16 peak TFLOP/s per chip, by TPU generation (fallback: v5e)
PEAK_BF16_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5 lite": 197.0,
                    "v5p": 459.0, "v6e": 918.0, "v6 lite": 918.0}

# HBM GB/s per chip, by TPU generation — the referent for MEMORY-bound
# phases (the elementwise optimizer update streams state; pricing it at
# the matmul peak would understate it by orders of magnitude)
HBM_GBPS = {"v4": 1228.0, "v5e": 819.0, "v5 lite": 819.0,
            "v5p": 2765.0, "v6e": 1640.0, "v6 lite": 1640.0}

_GiB = 1024 ** 3

# HBM CAPACITY bytes per chip, by TPU generation — the referent for the
# memlint OOM pre-flight gate (a predicted peak over this refuses the
# job before any chip time is spent). CPU hosts have no datasheet row:
# the gate there arms only from an explicit memlint.hbm_budget_bytes.
# v5p's datasheet 95 is decimal GB, not GiB — reading it as GiB would
# overstate the budget ~7.4 GB and let the gate pass a job that OOMs.
HBM_CAPACITY_BYTES = {"v4": 32 * _GiB, "v5e": 16 * _GiB,
                      "v5 lite": 16 * _GiB, "v5p": 95 * 10 ** 9,
                      "v6e": 32 * _GiB, "v6 lite": 32 * _GiB}


def chip_peak_tflops(device_kind: str,
                     default: Optional[float] = None) -> Optional[float]:
    """Peak bf16 TFLOP/s for a PJRT ``device_kind`` string; ``default``
    when the kind is unrecognized (CPU hosts have no meaningful peak)."""
    kind = (device_kind or "").lower()
    for key, peak in PEAK_BF16_TFLOPS.items():
        if key in kind:
            return peak
    return default


def chip_hbm_gbps(device_kind: str,
                  default: Optional[float] = None) -> Optional[float]:
    """Datasheet HBM GB/s for a PJRT ``device_kind``; ``default`` when
    unrecognized (CPU hosts: caller picks a documented host rate)."""
    kind = (device_kind or "").lower()
    for key, bw in HBM_GBPS.items():
        if key in kind:
            return bw
    return default


def chip_hbm_bytes(device_kind: str,
                   default: Optional[int] = None) -> Optional[int]:
    """Datasheet HBM capacity bytes for a PJRT ``device_kind``;
    ``default`` (usually None) when unrecognized — the datasheet-less
    CPU tier must opt in with an explicit budget, never inherit a TPU
    part's capacity."""
    kind = (device_kind or "").lower()
    for key, cap in HBM_CAPACITY_BYTES.items():
        if key in kind:
            return cap
    return default
