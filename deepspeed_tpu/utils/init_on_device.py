"""OnDevice: construct models abstractly ("meta") or on a chosen device.

Parity: reference ``utils/init_on_device.py`` (``OnDevice`` ctx — patches
tensor constructors so huge models materialize on the meta device or a target
device; used to defer allocation until ZeRO-3 partitioning is known).

TPU translation: parameter construction is already functional — the engine
calls ``jax.eval_shape`` on ``init_fn`` for planning and materializes
directly INTO the sharded layout (``jax.jit(init, out_shardings=...)``), so
the reference's deferred-allocation problem does not arise. This module
provides the same *API shape* for user code:

* ``OnDevice(device='meta')``: inside the context, :func:`materialize`
  returns ``ShapeDtypeStruct`` trees (no memory);
* ``OnDevice(device=...jax.Device..., dtype=...)``: materializes on that
  device in that dtype.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

PyTree = Any

_CURRENT: list = []


class OnDevice(contextlib.AbstractContextManager):
    def __init__(self, dtype: Any = None, device: Any = "meta",
                 enabled: bool = True):
        self.dtype = jnp.dtype(dtype) if dtype is not None else None
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        if self.enabled:
            _CURRENT.append(self)
        return self

    def __exit__(self, *exc):
        if self.enabled:
            _CURRENT.pop()
        return False


def current_on_device() -> Optional[OnDevice]:
    return _CURRENT[-1] if _CURRENT else None


def materialize(init_fn: Callable[[jax.Array], PyTree],
                rng: Optional[jax.Array] = None) -> PyTree:
    """Run ``init_fn`` honoring the active :class:`OnDevice` context."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    ctx = current_on_device()
    if ctx is None:
        return init_fn(rng)
    if ctx.device == "meta":
        shapes = jax.eval_shape(init_fn, rng)
        if ctx.dtype is not None:
            shapes = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, ctx.dtype), shapes)
        return shapes
    out = jax.jit(init_fn)(rng)
    if ctx.dtype is not None:
        out = jax.tree.map(lambda x: x.astype(ctx.dtype), out)
    if ctx.device is not None:
        out = jax.device_put(out, ctx.device)
    return out
