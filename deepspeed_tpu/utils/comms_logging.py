"""Per-collective size/latency/bandwidth statistics.

Parity: reference ``deepspeed/utils/comms_logging.py`` (``CommsLogger`` with
msg-size buckets, ``log_summary``). On TPU, collectives issued inside a traced
program have no host-visible per-op latency; for those we record op counts and
message sizes at trace time (exact, from static shapes) and estimate algorithmic
bandwidth only for eagerly-executed (host-level) collectives where wall time is
measurable. In-depth per-collective device timing comes from ``jax.profiler``.
"""
from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

def _tm():
    """Telemetry handles: CommsLogger totals fold into the unified registry
    (``comm_collectives_total`` / ``comm_bytes_total`` by op, plus measured
    latency for eager host-level collectives) so per-collective volume is a
    scrapeable metric, not just a log_summary line. Looked up fresh each
    call (a locked dict get) so a test-time registry reset can't strand
    recordings in orphaned metric objects."""
    from deepspeed_tpu import telemetry

    return (
        telemetry.counter("comm_collectives_total",
                          "collective ops issued (traced + eager)"),
        telemetry.counter("comm_bytes_total",
                          "bytes moved by collective ops"),
        telemetry.histogram("comm_latency_seconds",
                            "wall time of eagerly-executed collectives"),
    )


def get_caller_func(frame_depth: int = 3) -> str:
    import sys

    try:
        return sys._getframe(frame_depth).f_code.co_name
    except ValueError:   # call stack shallower than frame_depth
        return "unknown"


def convert_size(size_bytes: float) -> str:
    if size_bytes <= 0:
        return "0B"
    units = ("B", "KB", "MB", "GB", "TB", "PB")
    i = min(int(math.floor(math.log(size_bytes, 1024))), len(units) - 1)
    return f"{round(size_bytes / 1024 ** i, 2)} {units[i]}"


def calc_bw_log(comm_op: str, size_bytes: int, duration_s: float, n: int) -> Dict[str, float]:
    """Algorithmic + bus bandwidth, matching the reference's formulas
    (``comms_logging.py`` ``calc_bw_log``): allreduce busbw scales by 2(n-1)/n,
    all_gather/reduce_scatter/all_to_all by (n-1)/n. The factor table lives
    in ``comm/bandwidth.py`` — ONE copy shared with ``utils/comm_bench`` and
    the compiled-collective ledger, so "busbw" means the same quantity in a
    CommsLogger summary, a bench row, and a step report."""
    from deepspeed_tpu.comm.bandwidth import bw_log

    return bw_log(comm_op, size_bytes, duration_s, max(n, 1))


class CommsLogger:
    def __init__(self, enabled: bool = False, verbose: bool = False, prof_all: bool = True,
                 prof_ops: Optional[List[str]] = None, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.prof_all = prof_all
        self.prof_ops = prof_ops or []
        self.debug = debug
        # comms_dict[op_name][msg_size] = [count, [latencies], [tputs], [busbws]]
        self.comms_dict: Dict[str, Dict[int, list]] = defaultdict(dict)
        self.traced_counts: Dict[str, int] = defaultdict(int)
        self.traced_bytes: Dict[str, int] = defaultdict(int)

    def configure(self, comms_config) -> None:
        self.enabled = comms_config.enabled
        self.verbose = comms_config.verbose
        self.prof_all = comms_config.prof_all
        self.prof_ops = comms_config.prof_ops
        self.debug = comms_config.debug

    def _should_log(self, record_name: str) -> bool:
        return self.enabled and (self.prof_all or record_name in self.prof_ops)

    def append_traced(self, raw_name: str, record_name: str, size_bytes: int) -> None:
        """Record a collective issued during tracing (no wall-time available)."""
        if not self._should_log(record_name):
            return
        self.traced_counts[record_name] += 1
        self.traced_bytes[record_name] += size_bytes
        counts, byts, _ = _tm()
        counts.inc(op=record_name, mode="traced")
        byts.inc(size_bytes, op=record_name, mode="traced")

    def append(self, raw_name: str, record_name: str, latency_s: float, size_bytes: int,
               group_size: int) -> None:
        if not self._should_log(record_name):
            return
        bw = calc_bw_log(raw_name, size_bytes, latency_s, group_size)
        per_size = self.comms_dict[record_name].setdefault(size_bytes, [0, [], [], []])
        per_size[0] += 1
        per_size[1].append(latency_s * 1000.0)
        per_size[2].append(bw["tput_GBps"])
        per_size[3].append(bw["busbw_GBps"])
        counts, byts, lat = _tm()
        counts.inc(op=record_name, mode="eager")
        byts.inc(size_bytes, op=record_name, mode="eager")
        lat.observe(latency_s, op=record_name)
        if self.verbose:
            log_dist(
                f"comm op: {record_name} | time(ms): {latency_s * 1e3:.2f} | "
                f"msg size: {convert_size(size_bytes)} | algbw (Gbps): "
                f"{bw['tput_GBps'] * 8:.2f} | busbw (Gbps): {bw['busbw_GBps'] * 8:.2f}"
            )

    def log_summary(self, show_straggler: bool = False) -> str:
        lines = ["Comm. Op\tMessage Size\tCount\tTotal Latency(ms)\tAvg Latency(ms)"
                 "\ttput_avg (Gbps)\tbusbw_avg (Gbps)"]
        for op_name, sizes in sorted(self.comms_dict.items()):
            lines.append(op_name)
            for size_bytes, (count, lats, tputs, busbws) in sorted(sizes.items()):
                total = sum(lats)
                avg = total / max(count, 1)
                avg_tput = 8 * sum(tputs) / max(len(tputs), 1)
                avg_busbw = 8 * sum(busbws) / max(len(busbws), 1)
                lines.append(
                    f"\t\t\t{convert_size(size_bytes)}\t{count}\t{total:.2f}\t{avg:.2f}"
                    f"\t{avg_tput:.2f}\t{avg_busbw:.2f}")
        if self.traced_counts:
            lines.append("traced (in-jit) collectives: op\tcount\ttotal bytes")
            for op_name in sorted(self.traced_counts):
                lines.append(
                    f"\t{op_name}\t{self.traced_counts[op_name]}"
                    f"\t{convert_size(self.traced_bytes[op_name])}")
        summary = "\n".join(lines)
        log_dist(summary, ranks=[0])
        return summary

    def reset(self) -> None:
        self.comms_dict.clear()
        self.traced_counts.clear()
        self.traced_bytes.clear()
