def env_int(name: str, default: int) -> int:
    """int(os.environ[name]) with a warn-and-default on junk values — a
    malformed tuning knob must degrade to the default, not crash the
    training step (same defensive posture as the GMM tile fallback)."""
    import os
    import warnings

    val = os.environ.get(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        warnings.warn(f"{name}={val!r} is not an int — using {default}")
        return default
