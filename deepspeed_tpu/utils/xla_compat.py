"""XLA flag compatibility probes (stdlib-only; safe before jax backend init).

The CPU collective rendezvous deadline flags
(``--xla_cpu_collective_call_{warn_stuck,terminate}_timeout_seconds``) exist
only in some jaxlib builds; XLA hard-aborts (``F parse_flags_from_env``) on
unknown ``XLA_FLAGS`` at backend creation — which killed the whole test
session on builds without them. Probe once per jaxlib version in a throwaway
subprocess and cache the verdict in a temp marker so conftest/bench pay the
~2 s probe once per interpreter version, not per run.
"""
from __future__ import annotations

import os
import subprocess
import sys
import tempfile

CPU_COLLECTIVE_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")


def _jaxlib_version() -> str:
    try:
        import importlib.metadata as md

        return md.version("jaxlib")
    except ImportError:   # PackageNotFoundError subclasses ImportError
        return "unknown"


def supports_cpu_collective_timeout_flags() -> bool:
    marker = os.path.join(
        tempfile.gettempdir(),
        f".dstpu_xla_cc_timeout_flags_{_jaxlib_version()}")
    try:
        if os.path.exists(marker):
            with open(marker) as f:
                return f.read().strip() == "1"
    except OSError:
        pass
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=CPU_COLLECTIVE_TIMEOUT_FLAGS.strip())
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, env=env, timeout=120)
    except Exception as e:
        # transient failure (probe timeout on a loaded box, spawn error):
        # assume unsupported for THIS session but do NOT cache the verdict —
        # a permanent '0' would silently drop the rendezvous-timeout flags
        # on jaxlibs that support them. Say so: a session running without
        # the flags can flake with 'F rendezvous.cc:127' aborts, and that
        # must be attributable to this probe.
        import sys as _sys

        print(f"[xla_compat] collective-timeout flag probe failed "
              f"transiently ({e}); running this session WITHOUT the CPU "
              "rendezvous-timeout flags", file=_sys.stderr)
        return False
    ok = proc.returncode == 0
    # cache only deterministic outcomes: success, or XLA's explicit
    # unknown-flag abort; any other nonzero exit (OOM kill, SIGTERM) is
    # transient and must not poison future sessions
    flag_rejected = b"Unknown flags in XLA_FLAGS" in (proc.stderr or b"")
    if ok or flag_rejected:
        try:
            with open(marker, "w") as f:
                f.write("1" if ok else "0")
        except OSError:
            pass
    return ok


def cpu_collective_timeout_flags() -> str:
    """The flag string when this jaxlib accepts it, else '' (appendable to
    XLA_FLAGS unconditionally)."""
    return CPU_COLLECTIVE_TIMEOUT_FLAGS \
        if supports_cpu_collective_timeout_flags() else ""
