"""XLA flag compatibility probes (stdlib-only; safe before jax backend init).

The CPU collective rendezvous deadline flags
(``--xla_cpu_collective_call_{warn_stuck,terminate}_timeout_seconds``) exist
only in some jaxlib builds; XLA hard-aborts (``F parse_flags_from_env``) on
unknown ``XLA_FLAGS`` at backend creation — which killed the whole test
session on builds without them. Probe once per jaxlib version in a throwaway
subprocess and cache the verdict in a temp marker so conftest/bench pay the
~2 s probe once per interpreter version, not per run.

:func:`probe_xla_flags` is the generic form: any flag set can be vetted
the same way (``runtime/domino.py`` gates its overlap flags through it —
an unknown ``--xla_*`` on an older jaxlib is logged and skipped, never a
hard abort).
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
from typing import Optional, Sequence, Tuple

CPU_COLLECTIVE_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")


def _jaxlib_version() -> str:
    try:
        import importlib.metadata as md

        return md.version("jaxlib")
    except ImportError:   # PackageNotFoundError subclasses ImportError
        return "unknown"


def _probe_once(flags: str, platforms: str = "") -> Tuple[bool, bool, bytes]:
    """Spawn ``import jax; jax.devices()`` under ``XLA_FLAGS=flags``.

    → ``(accepted, deterministic, stderr)``: ``deterministic`` is False
    for transient failures (probe timeout, spawn error, OOM kill) which
    must not be cached — only a clean start or XLA's explicit
    unknown-flag abort is a verdict. ``stderr`` carries the abort text
    (XLA names the rejected flags in it)."""
    env = dict(os.environ, XLA_FLAGS=flags.strip())
    if platforms:
        env["JAX_PLATFORMS"] = platforms
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, env=env, timeout=120)
    except Exception as e:
        print(f"[xla_compat] XLA flag probe failed transiently ({e}); "
              f"treating {flags!r} as unsupported for THIS session",
              file=sys.stderr)
        return False, False, b""
    err = proc.stderr or b""
    if proc.returncode == 0:
        return True, True, err
    return False, b"Unknown flags in XLA_FLAGS" in err, err


def _verdicts_from_abort(flags: Sequence[str], stderr: bytes,
                         platforms: str) -> Optional[dict]:
    """Resolve per-flag verdicts from XLA's unknown-flag abort text.

    The abort line names every rejected flag; flags it names are
    unsupported, the rest are confirmed with ONE more whole-subset
    probe (a mis-parse must not smuggle a bad flag past the probe).
    Returns None when the line can't be matched to any flag name or the
    confirmation disagrees — callers then bisect per flag."""
    line = next((ln for ln in (stderr or b"").splitlines()
                 if b"Unknown flags in XLA_FLAGS" in ln), b"")
    rejected = [fl for fl in flags
                if fl.split("=", 1)[0].encode() in line]
    if not rejected:
        return None
    survivors = [fl for fl in flags if fl not in rejected]
    if survivors:
        ok, det, _ = _probe_once(" ".join(survivors), platforms)
        if not (ok and det):
            return None
    return {fl: fl not in rejected for fl in flags}


def probe_xla_flags(flags: Sequence[str],
                    platforms: str = "") -> Tuple[str, ...]:
    """Return the subset of ``flags`` this jaxlib's XLA accepts.

    One optimistic probe tries the whole set (the common all-supported
    case costs a single ~2 s subprocess). On an unknown-flag abort the
    rejected flags are read out of XLA's own abort line ("Unknown flags
    in XLA_FLAGS: ...") and the survivors re-probed ONCE to confirm —
    two subprocesses total; only if the abort text can't be matched to
    the flag names does it fall back to probing each flag individually.
    Verdicts cache per (jaxlib version, flag set) in a temp-dir JSON
    marker; transient probe failures return the empty set WITHOUT
    caching (a permanent "unsupported" from a loaded box would silently
    drop good flags forever)."""
    flags = tuple(flags)
    if not flags:
        return ()
    digest = hashlib.sha1(" ".join(flags).encode()).hexdigest()[:12]
    marker = os.path.join(
        tempfile.gettempdir(),
        f".dstpu_xla_flag_probe_{_jaxlib_version()}_{digest}")
    try:
        if os.path.exists(marker):
            with open(marker) as f:
                cached = json.load(f)
            return tuple(fl for fl in flags if cached.get(fl))
    except (OSError, ValueError):
        pass
    ok_all, deterministic, err = _probe_once(" ".join(flags), platforms)
    if ok_all:
        verdicts = {fl: True for fl in flags}
    elif not deterministic:
        return ()   # transient: no verdict, no cache
    else:
        verdicts = _verdicts_from_abort(flags, err, platforms)
        if verdicts is None:
            verdicts = {}
            for fl in flags:
                ok, det, _ = _probe_once(fl, platforms)
                if not det:
                    return ()   # transient mid-bisect: bail uncached
                verdicts[fl] = ok
    try:
        with open(marker, "w") as f:
            json.dump(verdicts, f)
    except OSError:
        pass
    return tuple(fl for fl in flags if verdicts[fl])


def supports_cpu_collective_timeout_flags() -> bool:
    flags = tuple(CPU_COLLECTIVE_TIMEOUT_FLAGS.split())
    # the rendezvous-timeout flags only make sense as a pair — partial
    # support (never observed in the wild) counts as unsupported
    return probe_xla_flags(flags, platforms="cpu") == flags


def cpu_collective_timeout_flags() -> str:
    """The flag string when this jaxlib accepts it, else '' (appendable to
    XLA_FLAGS unconditionally)."""
    return CPU_COLLECTIVE_TIMEOUT_FLAGS \
        if supports_cpu_collective_timeout_flags() else ""
