"""Rank-aware logging for SPMD JAX programs.

Capability parity with the reference's ``deepspeed/utils/logging.py`` (rank-aware
``logger`` + ``log_dist(ranks=[...])``), re-thought for SPMD: under JAX every host
runs the same program, so "rank" gating is by ``jax.process_index()`` rather than
an env-derived RANK.
"""
from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
            )
        )
        lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(), logging.INFO)
)


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    # jax.distributed not initialized / no backend yet -> single process.
    # This runs inside every log_dist call: logging about a logging
    # fallback would recurse/spam  # dslint: disable=silent-except
    except Exception:
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process indices (default: process 0).

    ``ranks=[-1]`` logs on every process. Mirrors the reference API
    (``deepspeed/utils/logging.py`` ``log_dist``).
    """
    ranks = ranks if ranks is not None else [0]
    me = _process_index()
    if -1 in ranks or me in ranks:
        logger.log(level, f"[proc {me}] {message}")


def warning_once(message: str) -> None:
    _warn_once_impl(message)


@functools.lru_cache(None)
def _warn_once_impl(message: str) -> None:
    logger.warning(message)


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)
