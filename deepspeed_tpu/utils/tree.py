"""Pytree mask utilities: prune/merge for frozen-parameter training.

Used by the LoRA path (``deepspeed_tpu/linear``): the optimizer sees only the
trainable subtree, so optimizer state (the ZeRO-dominant memory term) scales
with adapter size, not model size — the reference achieves the same via
LoRA-aware optimizer param groups (``linear/optimized_linear.py``).
Dict-structured trees only (the model-zoo convention).
"""
from __future__ import annotations

from typing import Any, Dict

PyTree = Any


def prune_tree(tree: PyTree, mask: PyTree) -> PyTree:
    """Keep only leaves whose mask is True; drop empty subtrees."""
    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            sub = prune_tree(v, mask[k])
            if sub is not None and (not isinstance(sub, dict) or sub):
                out[k] = sub
        return out
    return tree if mask else None


def merge_tree(full: PyTree, sub: PyTree, mask: PyTree) -> PyTree:
    """Overlay ``sub`` (a pruned tree) onto ``full`` where mask is True."""
    if isinstance(full, dict):
        return {k: (merge_tree(v, sub[k], mask[k])
                    if isinstance(sub, dict) and k in sub else v)
                for k, v in full.items()}
    return sub if mask else full


def mask_like(tree: PyTree, value: bool) -> PyTree:
    if isinstance(tree, dict):
        return {k: mask_like(v, value) for k, v in tree.items()}
    return value
