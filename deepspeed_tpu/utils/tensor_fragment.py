"""Tensor-fragment API: read/write full fp32 params, grads and optimizer
state of a live engine by parameter path.

Parity: reference ``utils/tensor_fragment.py`` (481 LoC mapping each rank's
flat-buffer fragments back to parameters: ``safe_get_full_fp32_param``,
``safe_set_full_fp32_param``, ``safe_get_full_optimizer_state``,
``safe_set_full_optimizer_state``, ``safe_get_full_grad`` — the debugging /
model-surgery API that hides ZeRO partitioning).

TPU translation: state lives as *global* sharded ``jax.Array`` trees, so
"defragmentation" is a gather (``device_get``) and a write is a sharded
``device_put`` — no offset arithmetic. Paths are '/'-joined tree keys, e.g.
``"blocks/wq"`` (list them with :func:`parameter_names`).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

PyTree = Any


def _walk(tree: PyTree, path: str):
    node = tree
    for part in path.split("/"):
        if isinstance(node, (list, tuple)):
            node = node[int(part)]
        else:
            node = node[part]
    return node


def _set(tree: PyTree, path: str, value) -> None:
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[int(part)] if isinstance(node, (list, tuple)) else node[part]
    last = parts[-1]
    if isinstance(node, list):
        node[int(last)] = value
    else:
        node[last] = value


def parameter_names(engine) -> List[str]:
    """All '/'-joined parameter paths of the engine's master tree."""
    out = []
    for path, _ in jax.tree_util.tree_flatten_with_path(
            engine.state["master"])[0]:
        out.append("/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                            for p in path))
    return out


def safe_get_full_fp32_param(engine, name: str) -> np.ndarray:
    """Full (gathered) fp32 master value of parameter ``name``
    (reference ``safe_get_full_fp32_param``)."""
    return np.asarray(jax.device_get(_walk(engine.state["master"], name)))


def safe_set_full_fp32_param(engine, name: str, value) -> None:
    """Overwrite a master parameter, preserving its sharded placement
    (reference ``safe_set_full_fp32_param``)."""
    current = _walk(engine.state["master"], name)
    arr = jax.numpy.asarray(value, dtype=current.dtype)
    if arr.shape != current.shape:
        raise ValueError(f"shape mismatch for {name!r}: "
                         f"{arr.shape} != {current.shape}")
    placed = jax.device_put(arr, current.sharding)
    _set(engine.state["master"], name, placed)


def safe_get_full_optimizer_state(engine, name: str, state_key: str
                                  ) -> np.ndarray:
    """Full value of one optimizer moment (e.g. 'exp_avg') for ``name``
    (reference ``safe_get_full_optimizer_state``)."""
    moments = engine.state["opt"]
    if state_key not in moments:
        raise KeyError(f"optimizer has no state {state_key!r}; "
                       f"available: {sorted(k for k in moments if k != 'step')}")
    return np.asarray(jax.device_get(_walk(moments[state_key], name)))


def safe_set_full_optimizer_state(engine, name: str, state_key: str,
                                  value) -> None:
    current = _walk(engine.state["opt"][state_key], name)
    arr = jax.numpy.asarray(value, dtype=current.dtype)
    if arr.shape != current.shape:
        raise ValueError(f"shape mismatch for {name}/{state_key}: "
                         f"{arr.shape} != {current.shape}")
    _set(engine.state["opt"][state_key], name, jax.device_put(arr, current.sharding))


def safe_get_full_grad(engine, name: str) -> Optional[np.ndarray]:
    """Accumulated gradient for ``name`` from the eager path's buffer
    (None when no grads are buffered — e.g. the fused train_batch path
    applies grads inside one program and never exposes them; reference
    ``safe_get_full_grad`` similarly requires grads to still exist)."""
    buf = getattr(engine, "_grad_buffer", None)
    if buf is None:
        return None
    return np.asarray(jax.device_get(_walk(buf, name)))


def state_summary(engine) -> Dict[str, Dict[str, Any]]:
    """{param: {shape, dtype, sharding}} — debugging aid."""
    out = {}
    for name in parameter_names(engine):
        leaf = _walk(engine.state["master"], name)
        out[name] = {"shape": tuple(leaf.shape), "dtype": str(leaf.dtype),
                     "sharding": str(getattr(leaf, "sharding", None))}
    return out
