"""Memory-space streaming utilities (ZeRO-Infinity parameter tier).

The engine parks stage-3 master shards in pinned host memory
(``offload_param``, reference ``swap_tensor/partitioned_param_swapper.py:37``)
and, on backends with in-program memories support, streams them H2D inside
the compiled step via :func:`stream_to_shardings` — always into the SHARDED
device layout (replicating the fp32 master would undo ZeRO-3), and always
OUTSIDE the autodiff (a device_put under ``grad`` transposes its cotangent
into host space). :func:`is_host_resident` is the trace-time test both the
engine's tier bookkeeping and the stream no-op check use — it only sees
memory spaces declared via explicit ``in_shardings``.
"""
from __future__ import annotations

from typing import Any

import jax

PyTree = Any


def is_host_resident(x: Any) -> bool:
    """Trace-time test: does this (possibly traced) array live in host
    memory space? Works on concrete arrays and tracers (sharding-in-types
    carries the memory space on the aval)."""
    aval = getattr(x, "aval", x)
    space = getattr(aval, "memory_space", None)
    return space is not None and "host" in str(space).lower()


def stream_to_shardings(tree: PyTree, shardings: PyTree) -> PyTree:
    """Move host-resident leaves onto device memory in a GIVEN layout
    (e.g. the ZeRO-3 sharded master spec — replicating would undo the
    sharding). Device-resident leaves pass through."""
    return jax.tree.map(
        lambda a, sh: jax.device_put(a, sh) if is_host_resident(a) else a,
        tree, shardings)


