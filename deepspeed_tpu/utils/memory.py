"""Memory-space streaming utilities (ZeRO-Infinity parameter tier).

The engine parks stage-3 master shards in pinned host memory
(``offload_param``, reference ``swap_tensor/partitioned_param_swapper.py:37``);
model code calls :func:`stream_to_device` on whatever params it is about to
use. For device-resident params it is a no-op (trace-time check — nothing is
added to the program); host-resident leaves get a ``device_put`` onto device
memory, which XLA's latency-hiding scheduler overlaps with compute when the
call sits inside a layer scan. The ``device_put`` transposes to the reverse
transfer (+ reduce-scatter for sharded hosts) in the backward pass.
"""
from __future__ import annotations

from typing import Any

import jax

PyTree = Any


def is_host_resident(x: Any) -> bool:
    """Trace-time test: does this (possibly traced) array live in host
    memory space? Works on concrete arrays and tracers (sharding-in-types
    carries the memory space on the aval)."""
    aval = getattr(x, "aval", x)
    space = getattr(aval, "memory_space", None)
    return space is not None and "host" in str(space).lower()


def stream_to_shardings(tree: PyTree, shardings: PyTree) -> PyTree:
    """Move host-resident leaves onto device memory in a GIVEN layout
    (e.g. the ZeRO-3 sharded master spec — replicating would undo the
    sharding). Device-resident leaves pass through."""
    return jax.tree.map(
        lambda a, sh: jax.device_put(a, sh) if is_host_resident(a) else a,
        tree, shardings)


def stream_to_device(tree: PyTree) -> PyTree:
    """Move host-resident leaves onto device memory, replicated — the
    ZeRO-3 "all-gather the params per use" applied as an H2D stream.
    Device-resident leaves pass through untouched (so this is safe to call
    unconditionally — under TP nothing gets force-replicated)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm.mesh import get_mesh_manager

    if not any(is_host_resident(leaf) for leaf in jax.tree.leaves(tree)):
        return tree
    try:
        mesh = get_mesh_manager().mesh
    except Exception:
        return tree
    dev = NamedSharding(mesh, P(), memory_kind="device")
    return jax.tree.map(
        lambda a: jax.device_put(a, dev) if is_host_resident(a) else a,
        tree)
