"""ModelSpec — the contract between user models and the engine.

The reference wraps ``nn.Module`` objects (``runtime/engine.py:235``); this
framework is functional, so a model is a triple of pure functions plus sharding
metadata. Adapters exist for the built-in transformer zoo (here) and flax modules
(``models/flax_adapter.py``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T

PyTree = Any
Batch = Union[jax.Array, Dict[str, jax.Array]]


@dataclasses.dataclass
class ModelSpec:
    init_fn: Callable[[jax.Array], PyTree]            # rng → fp32 params
    loss_fn: Callable[[PyTree, Batch], jax.Array]     # (compute params, batch) → scalar
    axes_fn: Callable[[], PyTree]                     # → logical-axes tree
    apply_fn: Optional[Callable[[PyTree, Batch], Any]] = None  # → model outputs
    name: str = "model"
    num_params: Optional[int] = None
    seq_len: Optional[int] = None  # nominal sequence length (profiling etc.)
    config: Any = None             # underlying model config (zoo: TransformerConfig)
    trainable_fn: Optional[Callable[[], PyTree]] = None  # bool tree; None = all trainable
    # optional explicit (loss, grads) path — used by schedules whose backward
    # cannot be derived by autodiff over the loss (1F1B pipeline). Called as
    # fn(compute_params, batch, loss_scale); returning None falls back to
    # value_and_grad over loss_fn. The decision must be trace-static.
    loss_and_grads_fn: Optional[Callable] = None
    # optional self-rebuild factory: fn(attention=None, loss_tiles=0) →
    # an equivalent ModelSpec with those knobs changed, preserving every
    # customization (LoRA adapters, imported weights, trainable masks...).
    # AutoSP uses this to swap the attention mechanism; specs without a
    # builder are left untouched (plan disabled).
    builder: Optional[Callable[..., "ModelSpec"]] = None


def _tokens_of(batch: Batch) -> jax.Array:
    if isinstance(batch, dict):
        return batch["tokens"]
    return batch


def _mask_of(batch: Batch):
    if isinstance(batch, dict):
        return batch.get("loss_mask")
    return None


def resolve_attention(attention: Optional[str]):
    """Named attention impls:

    * 'xla' (default) — XLA-fused reference attention
    * 'flash' — Pallas kernel (ops/pallas/flash_attention.py)
    * 'ulysses' / 'ulysses_flash' — all-to-all SP around xla/flash inner attention
    * 'ring' — KV-ring context parallelism over the 'seq' axis
    * 'chunked' — FPDT-style query-chunked attention (memory-capped)
    """
    if attention in (None, "xla", "default"):
        return None
    if attention == "flash":
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention
    if attention == "ulysses":
        from deepspeed_tpu.sequence import ulysses_attention

        return ulysses_attention()
    if attention == "ulysses_flash":
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        from deepspeed_tpu.sequence import ulysses_attention

        return ulysses_attention(inner=flash_attention)
    if attention == "ring":
        from deepspeed_tpu.sequence import ring_attention

        return ring_attention()
    if attention == "chunked":
        from deepspeed_tpu.sequence import chunked_attention

        return chunked_attention
    if attention == "fpdt":
        from deepspeed_tpu.sequence.tiled import fpdt_attention

        return fpdt_attention
    if attention.startswith("sparse"):
        # 'sparse' | 'sparse:fixed' | 'sparse:bigbird' | 'sparse:bslongformer'
        # (reference ops/sparse_attention SparseSelfAttention patterns)
        from deepspeed_tpu.ops.pallas import block_sparse as bs

        kind = attention.split(":", 1)[1] if ":" in attention else "fixed"
        builders = {"fixed": bs.fixed_layout, "bigbird": bs.bigbird_layout,
                    "bslongformer": bs.bslongformer_layout}
        if kind not in builders:
            raise ValueError(f"unknown sparse pattern {kind!r}; "
                             f"supported: {sorted(builders)}")

        def sparse_attn(q, k, v, causal=True, block_size=64):
            # model layout is [B, S, N, D]; kernel wants [B, N, S, D]
            if k.shape[2] != q.shape[2]:  # GQA: repeat kv heads
                rep = q.shape[2] // k.shape[2]
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            lay = builders[kind](q.shape[1] // block_size)
            if causal:
                lay = bs.causal_layout(lay)
            out = bs.block_sparse_attention(
                q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                v.transpose(0, 2, 1, 3), lay, block_size, causal=causal)
            return out.transpose(0, 2, 1, 3)

        return sparse_attn
    raise ValueError(f"unknown attention impl {attention!r}")


def causal_lm_spec(cfg: Union[str, T.TransformerConfig],
                   attention_fn=None, activation_constraint=None,
                   attention: Optional[str] = None,
                   loss_tiles: int = 0,
                   loss_impl: str = "fused",
                   pipeline_schedule: str = "1f1b",
                   pipeline_micro_batches: Optional[int] = None,
                   param_sync_fn=None,
                   **overrides) -> ModelSpec:
    """Build a ModelSpec for a causal-LM transformer preset or config.

    ``loss_tiles > 1`` computes the LM loss over sequence tiles without
    materializing full logits (ALST TiledFusedLogitsLoss analog,
    reference ``runtime/sequence_parallel/ulysses_sp.py:1065``).
    PRECEDENCE: tiling takes priority over ``loss_impl`` — a tiled loss
    uses exact fp32 tile numerics, NOT the fused bf16-logit path
    (``loss_impl`` only selects between fused/exact when untiled; the two
    knobs answer different questions: memory class vs numerics class).
    ``pipeline_schedule``: '1f1b' (explicit backward, O(stages) activation
    memory — reference ``runtime/pipe/schedule.py:189``) or 'gpipe'
    (autodiff-reversed wavefront, O(microbatches)); only used when the mesh
    has a 'pipe' axis > 1. ``pipeline_micro_batches`` sets the schedule's
    microbatch count M (reference ``pipeline.micro_batches``): the fill/
    drain bubble is (P-1)/(M+P-1), so M ≫ P amortizes it; default M = P.
    ``param_sync_fn`` (engine-injected; ``parallel/overlap.make_grad_sync``)
    wraps each layer-scan chunk's params so gradient sync is emitted
    mid-backward — pair with the ``scan_chunks`` config override."""
    if attention_fn is not None and attention is not None:
        raise ValueError("pass either attention_fn or attention=, not both")
    if loss_impl not in ("fused", "exact"):
        raise ValueError(f"unknown loss_impl {loss_impl!r}; one of "
                         "fused|exact (a typo must not silently change the "
                         "loss numerics/perf class)")
    if attention_fn is None:
        attention_fn = resolve_attention(attention)
    if isinstance(cfg, str):
        name = cfg
        cfg = T.get_model_config(cfg, **overrides)
    else:
        name = "transformer"
        if overrides:
            cfg = dataclasses.replace(cfg, **overrides)

    def _pipe_stages() -> int:
        from deepspeed_tpu.comm.mesh import PIPE_AXIS, maybe_mesh

        mesh = maybe_mesh()
        return mesh.shape.get(PIPE_AXIS, 1) if mesh is not None else 1

    def loss_fn(params, batch):
        tokens = _tokens_of(batch)
        if _pipe_stages() > 1:
            loss, aux = T.pipelined_lm_loss(
                params, tokens, cfg, attention_fn=attention_fn,
                activation_constraint=activation_constraint,
                loss_mask=_mask_of(batch),
                n_micro=pipeline_micro_batches)
            if cfg.n_experts > 0:
                loss = loss + cfg.moe_aux_coef * aux
            return loss
        # engine-injected data-efficiency controls (PLD mask, random-LTD
        # kept-token indices) ride the batch dict under underscore keys
        pld_keep = batch.get("_pld_keep") if isinstance(batch, dict) else None
        ltd_idx = batch.get("_random_ltd_idx") if isinstance(batch, dict) \
            else None
        hidden, head, aux = T.forward_hidden(
            params, tokens, cfg, attention_fn=attention_fn,
            activation_constraint=activation_constraint,
            pld_keep=pld_keep, random_ltd_idx=ltd_idx,
            param_sync=param_sync_fn)
        if loss_tiles > 1:
            from deepspeed_tpu.sequence.tiled import tiled_lm_loss

            loss = tiled_lm_loss(hidden, head, tokens, _mask_of(batch),
                                 num_tiles=loss_tiles)
        elif loss_impl == "fused":
            # default training loss: bf16 logits + fp32 softmax stats with
            # a bandwidth-tuned custom VJP (torch-autocast CE semantics —
            # the exact-fp32-logits path stays under loss_impl="exact";
            # inference/apply_fn logits are always exact fp32)
            loss = T.fused_lm_loss(hidden, head, tokens, _mask_of(batch))
        else:
            logits = T.head_matmul(hidden, head.astype(hidden.dtype))
            loss = T.causal_lm_loss(logits, tokens, _mask_of(batch))
        if cfg.n_experts > 0:
            loss = loss + cfg.moe_aux_coef * aux
        return loss

    def apply_fn(params, batch):
        return T.forward(params, _tokens_of(batch), cfg,
                         attention_fn=attention_fn,
                         activation_constraint=activation_constraint)

    def loss_and_grads_fn(params, batch, loss_scale=None):
        if pipeline_schedule != "1f1b" or _pipe_stages() <= 1:
            return None   # engine falls back to value_and_grad(loss_fn)
        return T.pipelined_lm_loss_and_grads(
            params, _tokens_of(batch), cfg, attention_fn=attention_fn,
            activation_constraint=activation_constraint,
            loss_mask=_mask_of(batch), loss_scale=loss_scale,
            n_micro=pipeline_micro_batches)

    user_attention_fn = attention_fn is not None and attention is None
    orig_loss_tiles = loss_tiles
    orig_attention = attention
    orig_param_sync = param_sync_fn

    def _rebuild(attention: Optional[str] = None,
                 loss_tiles: int = 0,
                 remat: Optional[str] = None,
                 act_quant_bits: Optional[int] = None,
                 scan_chunks: Optional[int] = None,
                 param_sync_fn=None) -> "ModelSpec":
        # keep the stronger loss tiling of (original, requested) — AutoSP
        # must not untile a loss the user tiled to avoid full logits; an
        # unspecified attention keeps the original named mechanism.
        # act_quant_bits threads QAT activation quantization into the block
        # forward (compression/compress.py init_compression).
        # scan_chunks/param_sync_fn: the engine's overlap-scheduler rebuild
        # (chunked layer scan + mid-backward grad sync); None keeps the
        # original spec's values.
        cfg_over = {}
        if remat:
            cfg_over["remat"] = remat
        if act_quant_bits is not None:
            cfg_over["act_quant_bits"] = act_quant_bits
        if scan_chunks is not None:
            cfg_over["scan_chunks"] = int(scan_chunks)
        cfg2 = dataclasses.replace(cfg, **cfg_over) if cfg_over else cfg
        return causal_lm_spec(cfg2,
                              attention=attention or orig_attention,
                              loss_tiles=max(loss_tiles, orig_loss_tiles),
                              loss_impl=loss_impl,
                              activation_constraint=activation_constraint,
                              pipeline_schedule=pipeline_schedule,
                              param_sync_fn=param_sync_fn or orig_param_sync)

    return ModelSpec(
        init_fn=lambda rng: T.init_params(cfg, rng),
        loss_fn=loss_fn,
        apply_fn=apply_fn,
        axes_fn=lambda: T.param_logical_axes(cfg),
        name=name,
        num_params=cfg.num_params(),
        seq_len=cfg.max_seq_len,
        config=cfg,
        loss_and_grads_fn=loss_and_grads_fn,
        # a hand-written attention_fn has semantics a rewrite can't preserve
        # (sliding window, custom bias...) — no builder, so AutoSP declines
        builder=None if user_attention_fn else _rebuild,
    )


def spec_from_hf(model, arch: Optional[str] = None, attention: Optional[str] = None,
                 loss_tiles: int = 0, **overrides) -> ModelSpec:
    """Build a trainable ModelSpec from a HuggingFace model (or
    ``(state_dict, config)`` pair): weights are imported once
    (``models/hf_import.py``) and become the spec's initial parameters.

    The reference's equivalent is passing an HF model straight to
    ``deepspeed.initialize`` — here interop happens at the weight level."""
    import dataclasses as _dc

    import jax.numpy as _jnp

    from deepspeed_tpu.models.hf_import import import_hf_model

    cfg, params = import_hf_model(model, arch=arch)
    if overrides:
        cfg = _dc.replace(cfg, **overrides)
    base = causal_lm_spec(cfg, attention=attention, loss_tiles=loss_tiles)
    init_params = jax.tree.map(lambda x: _jnp.asarray(x, _jnp.float32), params)
    name = getattr(getattr(model, "config", None), "model_type", None) \
        or (arch or "hf_model")

    def _rebuild(attention: Optional[str] = None,
                 loss_tiles: int = 0,
                 remat: Optional[str] = None, **kwargs) -> ModelSpec:
        # **kwargs: scan_chunks / param_sync_fn etc. — forwarded so the
        # engine's overlap rebuild works on imported-weight specs too
        nb = base.builder(attention=attention, loss_tiles=loss_tiles,
                          remat=remat, **kwargs)
        return _dc.replace(nb, init_fn=lambda rng: init_params,
                           name=str(name))

    return _dc.replace(base, init_fn=lambda rng: init_params, name=str(name),
                       builder=_rebuild)
