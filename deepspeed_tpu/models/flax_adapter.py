"""Flax adapter — run any ``flax.linen`` module under the engine.

Role: the reference wraps arbitrary ``nn.Module``s (HF models, Megatron
models) in ``deepspeed.initialize``; the TPU framework's equivalent "bring
your own model" path accepts a flax module and adapts it to the
:class:`~deepspeed_tpu.models.api.ModelSpec` contract. Logical sharding axes
default to unannotated (ZeRO still shards each leaf's largest divisible dim —
``parallel/partitioning.py _add_zero_axis``); pass ``axes`` to enable TP on
specific parameters.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.api import ModelSpec

PyTree = Any


def _default_axes(params: PyTree) -> PyTree:
    return jax.tree.map(lambda p: (None,) * jnp.ndim(p), params)


def flax_model_spec(module, example_batch: Dict[str, jax.Array],
                    loss_fn: Optional[Callable] = None,
                    axes: Optional[PyTree] = None,
                    name: Optional[str] = None,
                    batch_key: str = "tokens") -> ModelSpec:
    """Adapt a flax module to a ModelSpec.

    * ``module(tokens) -> logits`` (causal-LM convention); for other tasks
      pass a custom ``loss_fn(logits_or_outputs, batch) -> scalar``.
    * ``example_batch`` supplies init-time shapes/dtypes (shapes only matter
      up to the batch dim).
    """
    example_in = example_batch[batch_key]

    def init_fn(rng):
        variables = module.init(rng, example_in)
        params = variables.get("params", variables)
        # fp32 master copies regardless of module dtype
        return jax.tree.map(lambda p: jnp.asarray(p, jnp.float32), params)

    def apply_fn(params, batch):
        x = batch[batch_key] if isinstance(batch, dict) else batch
        return module.apply({"params": params}, x)

    if loss_fn is None:
        from deepspeed_tpu.models.transformer import causal_lm_loss

        def default_loss(params, batch):
            tokens = batch[batch_key] if isinstance(batch, dict) else batch
            logits = apply_fn(params, batch)
            mask = batch.get("loss_mask") if isinstance(batch, dict) else None
            return causal_lm_loss(logits, tokens, mask)

        spec_loss = default_loss
    else:
        def spec_loss(params, batch):
            return loss_fn(apply_fn(params, batch), batch)

    shapes = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    axes_tree = _default_axes(shapes) if axes is None else axes
    n_params = sum(math.prod(l.shape) for l in jax.tree.leaves(shapes))

    return ModelSpec(
        init_fn=init_fn,
        loss_fn=spec_loss,
        apply_fn=apply_fn,
        axes_fn=lambda: axes_tree,
        name=name or type(module).__name__,
        num_params=n_params,
        seq_len=int(example_in.shape[1]) if example_in.ndim > 1 else None,
    )
