from deepspeed_tpu.models.api import ModelSpec, causal_lm_spec
from deepspeed_tpu.models.transformer import (
    PRESETS,
    TransformerConfig,
    causal_lm_loss,
    forward,
    get_model_config,
    init_params,
    param_logical_axes,
)
