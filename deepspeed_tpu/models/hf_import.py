"""Import HuggingFace transformer weights into the model zoo.

Role: the reference consumes HF models directly (AutoTP
``module_inject/auto_tp.py``, checkpoint loading ``inference/engine.py:303``,
FastGen's per-arch implementations ``inference/v2/model_implementations``).
This framework is torch-free at runtime, so interop happens at the weight
level: convert an HF state dict (torch CPU tensors) into the zoo's
layer-stacked param pytree once, then everything — ZeRO, TP, inference —
works on it.

Supported architectures: gpt2, llama (mistral shares the schema), mixtral
(MoE). Conventions verified by logit-matching tests against ``transformers``:
* HF ``nn.Linear`` weights are [out, in] → transposed; GPT-2's ``Conv1D`` is
  already [in, out] → copied as-is.
* Llama RoPE uses the rotate-half (non-interleaved) convention — identical to
  ``transformer.apply_rope``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig

PyTree = Any


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _stack(sd: Dict[str, Any], fmt: str, L: int, transpose: bool = False
           ) -> np.ndarray:
    mats = [_np(sd[fmt.format(i)]) for i in range(L)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


# --------------------------------------------------------------------------- #
# GPT-2
# --------------------------------------------------------------------------- #

def config_from_gpt2(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        pos_emb="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=hf_config.layer_norm_epsilon, dtype="float32")


def params_from_gpt2(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, H = cfg.num_layers, cfg.hidden_size
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    # Conv1D c_attn: [H, 3H] (in, out) — split into q/k/v without transposing
    c_attn = _stack(sd, pre + "h.{}.attn.c_attn.weight", L)       # [L, H, 3H]
    b_attn = _stack(sd, pre + "h.{}.attn.c_attn.bias", L)         # [L, 3H]
    blocks = {
        "ln1": {"scale": _stack(sd, pre + "h.{}.ln_1.weight", L),
                "bias": _stack(sd, pre + "h.{}.ln_1.bias", L)},
        "ln2": {"scale": _stack(sd, pre + "h.{}.ln_2.weight", L),
                "bias": _stack(sd, pre + "h.{}.ln_2.bias", L)},
        "wq": c_attn[:, :, :H], "wk": c_attn[:, :, H:2 * H],
        "wv": c_attn[:, :, 2 * H:],
        "bq": b_attn[:, :H], "bk": b_attn[:, H:2 * H], "bv": b_attn[:, 2 * H:],
        "wo": _stack(sd, pre + "h.{}.attn.c_proj.weight", L),
        "bo": _stack(sd, pre + "h.{}.attn.c_proj.bias", L),
        "w_up": _stack(sd, pre + "h.{}.mlp.c_fc.weight", L),
        "b_up": _stack(sd, pre + "h.{}.mlp.c_fc.bias", L),
        "w_down": _stack(sd, pre + "h.{}.mlp.c_proj.weight", L),
        "b_down": _stack(sd, pre + "h.{}.mlp.c_proj.bias", L),
    }
    return {
        "tok_emb": _np(sd[pre + "wte.weight"]),
        "pos_emb": _np(sd[pre + "wpe.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "ln_f.weight"]),
                       "bias": _np(sd[pre + "ln_f.bias"])},
    }


# --------------------------------------------------------------------------- #
# Llama / Mistral
# --------------------------------------------------------------------------- #

def config_from_llama(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="rmsnorm", activation="swiglu",
        use_bias=False,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=hf_config.rms_norm_eps, dtype="float32")


def params_from_llama(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L)},
        "wq": _stack(sd, lyr + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, lyr + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, lyr + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, lyr + "self_attn.o_proj.weight", L, transpose=True),
        "w_gate": _stack(sd, lyr + "mlp.gate_proj.weight", L, transpose=True),
        "w_up": _stack(sd, lyr + "mlp.up_proj.weight", L, transpose=True),
        "w_down": _stack(sd, lyr + "mlp.down_proj.weight", L, transpose=True),
    }
    params = {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return params


# --------------------------------------------------------------------------- #
# Mixtral (Llama schema + MoE FFN)
# --------------------------------------------------------------------------- #

def config_from_mixtral(hf_config) -> TransformerConfig:
    cfg = config_from_llama(hf_config)
    return dataclasses.replace(
        cfg,
        n_experts=hf_config.num_local_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.02)))


def params_from_mixtral(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, E = cfg.num_layers, cfg.n_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    moe = lyr + "block_sparse_moe."

    def experts(wname):  # HF w1=gate, w2=down, w3=up; nn.Linear [out,in]
        return np.stack([
            np.stack([_np(sd[moe.format(i) + f"experts.{e}.{wname}.weight"]).T
                      for e in range(E)])
            for i in range(L)])

    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L)},
        "wq": _stack(sd, lyr + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, lyr + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, lyr + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, lyr + "self_attn.o_proj.weight", L, transpose=True),
        "gate_w": _stack(sd, moe + "gate.weight", L, transpose=True),
        "w_gate": experts("w1"),
        "w_down": experts("w2"),
        "w_up": experts("w3"),
    }
    params = {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return params


# --------------------------------------------------------------------------- #
# front door
# --------------------------------------------------------------------------- #

_ARCH_TABLE = {
    "gpt2": (config_from_gpt2, params_from_gpt2),
    "llama": (config_from_llama, params_from_llama),
    "mistral": (config_from_llama, params_from_llama),
    "mixtral": (config_from_mixtral, params_from_mixtral),
}


def import_hf_model(model, arch: Optional[str] = None
                    ) -> Tuple[TransformerConfig, PyTree]:
    """Convert a ``transformers`` model (or (state_dict, config) pair) into
    (TransformerConfig, zoo params)."""
    if isinstance(model, tuple):
        sd, hf_config = model
    else:
        sd, hf_config = model.state_dict(), model.config
    arch = arch or getattr(hf_config, "model_type", None)
    if arch not in _ARCH_TABLE:
        raise ValueError(
            f"unsupported HF architecture {arch!r}; "
            f"supported: {sorted(_ARCH_TABLE)}")
    cfg_fn, params_fn = _ARCH_TABLE[arch]
    cfg = cfg_fn(hf_config)
    return cfg, params_fn(sd, cfg)
