"""Import HuggingFace transformer weights into the model zoo.

Role: the reference consumes HF models directly (AutoTP
``module_inject/auto_tp.py``, checkpoint loading ``inference/engine.py:303``,
FastGen's per-arch implementations ``inference/v2/model_implementations``).
This framework is torch-free at runtime, so interop happens at the weight
level: convert an HF state dict (torch CPU tensors) into the zoo's
layer-stacked param pytree once, then everything — ZeRO, TP, inference —
works on it.

Supported architectures: gpt2, llama (mistral shares the schema), mixtral
(MoE). Conventions verified by logit-matching tests against ``transformers``:
* HF ``nn.Linear`` weights are [out, in] → transposed; GPT-2's ``Conv1D`` is
  already [in, out] → copied as-is.
* Llama RoPE uses the rotate-half (non-interleaved) convention — identical to
  ``transformer.apply_rope``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig

PyTree = Any


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, np.float32)


def _stack(sd: Dict[str, Any], fmt: str, L: int, transpose: bool = False
           ) -> np.ndarray:
    mats = [_np(sd[fmt.format(i)]) for i in range(L)]
    if transpose:
        mats = [m.T for m in mats]
    return np.stack(mats)


def _canon_rope_scaling(hf_config) -> Optional[tuple]:
    """HF rope_scaling dict → canonical hashable tuple for the frozen zoo
    config; validates the type is one the zoo implements
    (``transformer._scaled_inv_freq``: default/linear/llama3/yarn) by raising
    the zoo's NotImplementedError for anything else — silently ignoring
    scaling would mean wrong logits on every real Llama-3/DeepSeek
    checkpoint."""
    rs = getattr(hf_config, "rope_scaling", None)
    if not rs:
        return None
    sc = {k: v for k, v in dict(rs).items() if v is not None}
    # yarn falls back to the model's max positions when 'original_...' absent
    sc.setdefault("max_position_embeddings",
                  getattr(hf_config, "max_position_embeddings", 2048))
    from deepspeed_tpu.models.transformer import _scaled_inv_freq

    _scaled_inv_freq(64, 10000.0, sc)   # type/keys validation
    return tuple(sorted(sc.items()))


# --------------------------------------------------------------------------- #
# GPT-2
# --------------------------------------------------------------------------- #

def config_from_gpt2(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.n_embd,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=hf_config.n_positions,
        pos_emb="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=hf_config.layer_norm_epsilon, dtype="float32")


def params_from_gpt2(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, H = cfg.num_layers, cfg.hidden_size
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""

    # Conv1D c_attn: [H, 3H] (in, out) — split into q/k/v without transposing
    c_attn = _stack(sd, pre + "h.{}.attn.c_attn.weight", L)       # [L, H, 3H]
    b_attn = _stack(sd, pre + "h.{}.attn.c_attn.bias", L)         # [L, 3H]
    blocks = {
        "ln1": {"scale": _stack(sd, pre + "h.{}.ln_1.weight", L),
                "bias": _stack(sd, pre + "h.{}.ln_1.bias", L)},
        "ln2": {"scale": _stack(sd, pre + "h.{}.ln_2.weight", L),
                "bias": _stack(sd, pre + "h.{}.ln_2.bias", L)},
        "wq": c_attn[:, :, :H], "wk": c_attn[:, :, H:2 * H],
        "wv": c_attn[:, :, 2 * H:],
        "bq": b_attn[:, :H], "bk": b_attn[:, H:2 * H], "bv": b_attn[:, 2 * H:],
        "wo": _stack(sd, pre + "h.{}.attn.c_proj.weight", L),
        "bo": _stack(sd, pre + "h.{}.attn.c_proj.bias", L),
        "w_up": _stack(sd, pre + "h.{}.mlp.c_fc.weight", L),
        "b_up": _stack(sd, pre + "h.{}.mlp.c_fc.bias", L),
        "w_down": _stack(sd, pre + "h.{}.mlp.c_proj.weight", L),
        "b_down": _stack(sd, pre + "h.{}.mlp.c_proj.bias", L),
    }
    return {
        "tok_emb": _np(sd[pre + "wte.weight"]),
        "pos_emb": _np(sd[pre + "wpe.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "ln_f.weight"]),
                       "bias": _np(sd[pre + "ln_f.bias"])},
    }


# --------------------------------------------------------------------------- #
# Llama / Mistral
# --------------------------------------------------------------------------- #

def config_from_llama(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="rmsnorm", activation="swiglu",
        use_bias=False,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        rope_scaling=_canon_rope_scaling(hf_config),
        norm_eps=hf_config.rms_norm_eps, dtype="float32")


def params_from_llama(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    blocks, params = _llama_attn_blocks(sd, cfg, pre)
    blocks.update({
        "w_gate": _stack(sd, lyr + "mlp.gate_proj.weight", L, transpose=True),
        "w_up": _stack(sd, lyr + "mlp.up_proj.weight", L, transpose=True),
        "w_down": _stack(sd, lyr + "mlp.down_proj.weight", L, transpose=True),
    })
    params["blocks"] = blocks
    return params


# --------------------------------------------------------------------------- #
# Mixtral (Llama schema + MoE FFN)
# --------------------------------------------------------------------------- #

def config_from_mixtral(hf_config) -> TransformerConfig:
    cfg = config_from_llama(hf_config)
    return dataclasses.replace(
        cfg,
        n_experts=hf_config.num_local_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.02)))


def params_from_mixtral(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, E = cfg.num_layers, cfg.n_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    moe = pre + "layers.{}.block_sparse_moe."

    def experts(wname):  # HF w1=gate, w2=down, w3=up; nn.Linear [out,in]
        return np.stack([
            np.stack([_np(sd[moe.format(i) + f"experts.{e}.{wname}.weight"]).T
                      for e in range(E)])
            for i in range(L)])

    blocks, params = _llama_attn_blocks(sd, cfg, pre)
    blocks.update({
        "gate_w": _stack(sd, moe + "gate.weight", L, transpose=True),
        "w_gate": experts("w1"),
        "w_down": experts("w2"),
        "w_up": experts("w3"),
    })
    params["blocks"] = blocks
    return params



# --------------------------------------------------------------------------- #
# Qwen2 (Llama schema + attention biases)
# --------------------------------------------------------------------------- #

def config_from_qwen2(hf_config) -> TransformerConfig:
    cfg = config_from_llama(hf_config)
    return dataclasses.replace(cfg, qkv_bias=True)


def params_from_qwen2(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    params = params_from_llama(sd, cfg)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    params["blocks"].update({
        "bq": _stack(sd, lyr + "self_attn.q_proj.bias", L),
        "bk": _stack(sd, lyr + "self_attn.k_proj.bias", L),
        "bv": _stack(sd, lyr + "self_attn.v_proj.bias", L),
    })
    return params


# --------------------------------------------------------------------------- #
# Qwen2-MoE / Qwen3-MoE (AutoEP presets; reference module_inject/auto_ep_presets/
# {qwen3_moe,qwen3_5_moe}.py detection patterns — here realized as importers)
# --------------------------------------------------------------------------- #

def _assert_homogeneous_moe(hf_config) -> None:
    """The zoo scans a homogeneous layer stack; Qwen-MoE configs that mix
    dense and sparse layers (decoder_sparse_step > 1 or mlp_only_layers)
    can't be stacked."""
    step = int(getattr(hf_config, "decoder_sparse_step", 1) or 1)
    only = list(getattr(hf_config, "mlp_only_layers", []) or [])
    if step != 1 or only:
        raise NotImplementedError(
            f"heterogeneous MoE stack (decoder_sparse_step={step}, "
            f"mlp_only_layers={only}) is not supported by the stacked-layer "
            "zoo; every layer must be sparse")


def config_from_qwen2_moe(hf_config) -> TransformerConfig:
    _assert_homogeneous_moe(hf_config)
    cfg = config_from_llama(hf_config)
    return dataclasses.replace(
        cfg, qkv_bias=True,
        n_experts=hf_config.num_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_ffn_size=hf_config.moe_intermediate_size,
        moe_shared_size=hf_config.shared_expert_intermediate_size,
        moe_shared_gate=True,
        moe_route_norm=bool(hf_config.norm_topk_prob),
        moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.001)))


def _llama_attn_blocks(sd: Dict[str, Any], cfg: TransformerConfig,
                       pre: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Shared Llama-schema attention/norm/embedding pieces (no FFN)."""
    L = cfg.num_layers
    lyr = pre + "layers.{}."
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L)},
        "wq": _stack(sd, lyr + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, lyr + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, lyr + "self_attn.v_proj.weight", L, transpose=True),
        "wo": _stack(sd, lyr + "self_attn.o_proj.weight", L, transpose=True),
    }
    params = {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return blocks, params


def _qwen_moe_experts(sd: Dict[str, Any], moe_fmt: str, L: int, E: int):
    """Stack per-expert gate/up/down ModuleList weights → [L, E, in, out]."""
    def experts(wname):
        return np.stack([
            np.stack([_np(sd[moe_fmt.format(i) + f"experts.{e}.{wname}.weight"]).T
                      for e in range(E)])
            for i in range(L)])

    return {"w_gate": experts("gate_proj"), "w_up": experts("up_proj"),
            "w_down": experts("down_proj")}


def params_from_qwen2_moe(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, E = cfg.num_layers, cfg.n_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    moe = lyr + "mlp."
    blocks, params = _llama_attn_blocks(sd, cfg, pre)
    blocks.update({
        "bq": _stack(sd, lyr + "self_attn.q_proj.bias", L),
        "bk": _stack(sd, lyr + "self_attn.k_proj.bias", L),
        "bv": _stack(sd, lyr + "self_attn.v_proj.bias", L),
        "gate_w": _stack(sd, moe + "gate.weight", L, transpose=True),
        "sw_gate": _stack(sd, moe + "shared_expert.gate_proj.weight", L,
                          transpose=True),
        "sw_up": _stack(sd, moe + "shared_expert.up_proj.weight", L,
                        transpose=True),
        "sw_down": _stack(sd, moe + "shared_expert.down_proj.weight", L,
                          transpose=True),
        "shared_gate_w": _stack(sd, moe + "shared_expert_gate.weight", L,
                                transpose=True),
    })
    blocks.update(_qwen_moe_experts(sd, moe, L, E))
    params["blocks"] = blocks
    return params


def config_from_qwen3(hf_config) -> TransformerConfig:
    """Qwen3 dense: llama schema + QK-norm + explicit head_dim, no qkv bias."""
    cfg = config_from_llama(hf_config)
    return dataclasses.replace(
        cfg, qk_norm=True, attn_head_dim=getattr(hf_config, "head_dim", None))


def params_from_qwen3(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    params = params_from_llama(sd, cfg)
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    params["blocks"]["q_norm"] = _stack(sd, lyr + "self_attn.q_norm.weight", L)
    params["blocks"]["k_norm"] = _stack(sd, lyr + "self_attn.k_norm.weight", L)
    return params


def config_from_qwen3_moe(hf_config) -> TransformerConfig:
    _assert_homogeneous_moe(hf_config)
    cfg = config_from_llama(hf_config)
    head_dim = getattr(hf_config, "head_dim", None)
    return dataclasses.replace(
        cfg, qk_norm=True, attn_head_dim=head_dim,
        n_experts=hf_config.num_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_ffn_size=hf_config.moe_intermediate_size,
        moe_route_norm=bool(hf_config.norm_topk_prob),
        moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.001)))


def params_from_qwen3_moe(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, E = cfg.num_layers, cfg.n_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    moe = lyr + "mlp."
    blocks, params = _llama_attn_blocks(sd, cfg, pre)
    blocks.update({
        "gate_w": _stack(sd, moe + "gate.weight", L, transpose=True),
        "q_norm": _stack(sd, lyr + "self_attn.q_norm.weight", L),
        "k_norm": _stack(sd, lyr + "self_attn.k_norm.weight", L),
    })
    blocks.update(_qwen_moe_experts(sd, moe, L, E))
    params["blocks"] = blocks
    return params


# --------------------------------------------------------------------------- #
# DeepSeek V2/V3 (MLA attention + sigmoid/grouped routing + shared experts;
# AutoEP presets module_inject/auto_ep_presets/deepseek_v{2,3}.py)
# --------------------------------------------------------------------------- #

def config_from_deepseek_v3(hf_config) -> TransformerConfig:
    first_dense = int(getattr(hf_config, "first_k_dense_replace", 0) or 0)
    if first_dense > 0:
        raise NotImplementedError(
            f"first_k_dense_replace={first_dense}: heterogeneous dense/MoE "
            "stacks are not supported by the stacked-layer zoo")
    shared = int(getattr(hf_config, "n_shared_experts", 0) or 0)
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="rmsnorm", activation="swiglu", use_bias=False,
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=hf_config.rms_norm_eps, dtype="float32",
        rope_scaling=_canon_rope_scaling(hf_config),
        mla=True,
        q_lora_rank=getattr(hf_config, "q_lora_rank", None),
        kv_lora_rank=hf_config.kv_lora_rank,
        qk_nope_head_dim=hf_config.qk_nope_head_dim,
        qk_rope_head_dim=hf_config.qk_rope_head_dim,
        v_head_dim=hf_config.v_head_dim,
        rope_interleave=bool(getattr(hf_config, "rope_interleave", True)),
        n_experts=hf_config.n_routed_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_ffn_size=hf_config.moe_intermediate_size,
        moe_shared_size=shared * hf_config.moe_intermediate_size,
        moe_score_func="sigmoid",
        moe_route_norm=bool(hf_config.norm_topk_prob),
        moe_route_scale=float(getattr(hf_config, "routed_scaling_factor", 1.0)),
        moe_gate_bias=True,
        moe_n_group=int(getattr(hf_config, "n_group", 1) or 1),
        moe_topk_group=int(getattr(hf_config, "topk_group", 1) or 1),
        moe_aux_coef=float(getattr(hf_config, "router_aux_loss_coef", 0.001)))


def config_from_deepseek_v2(hf_config) -> TransformerConfig:
    """DeepSeek-V2/V2-Lite: same MLA as V3; softmax greedy routing,
    non-interleaved rope, no gate bias. Derives from the V3 mapping and
    overrides the family differences (codebase convention: qwen variants
    derive from config_from_llama the same way)."""
    scoring = getattr(hf_config, "scoring_func", "softmax") or "softmax"
    if scoring != "softmax":
        raise NotImplementedError(
            f"deepseek_v2 scoring_func={scoring!r}: the V2 importer maps "
            "softmax routing; sigmoid-scored configs belong to the "
            "deepseek_v3 importer")
    method = getattr(hf_config, "topk_method", "greedy")
    if method != "greedy":
        raise NotImplementedError(
            f"deepseek_v2 topk_method={method!r}: only 'greedy' routing is "
            "supported (the group-limited variant scores groups by max, "
            "unlike V3's top-2 sum)")
    cfg = config_from_deepseek_v3(hf_config)
    return dataclasses.replace(
        cfg, rope_interleave=False, moe_score_func="softmax",
        moe_gate_bias=False, moe_n_group=1, moe_topk_group=1)


def params_from_deepseek(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    """Shared V2/V3 weight mapping (V3 adds gate.e_score_correction_bias)."""
    L, E = cfg.num_layers, cfg.n_experts
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    attn = lyr + "self_attn."
    moe = lyr + "mlp."
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L)},
        "wkv_a": _stack(sd, attn + "kv_a_proj_with_mqa.weight", L,
                        transpose=True),
        "kv_a_norm": _stack(sd, attn + "kv_a_layernorm.weight", L),
        "wkv_b": _stack(sd, attn + "kv_b_proj.weight", L, transpose=True),
        "wo": _stack(sd, attn + "o_proj.weight", L, transpose=True),
        "gate_w": _stack(sd, moe + "gate.weight", L, transpose=True),
    }
    if cfg.moe_gate_bias:
        blocks["gate_bias"] = _stack(
            sd, moe + "gate.e_score_correction_bias", L)
    if cfg.moe_shared_size > 0:
        blocks["sw_gate"] = _stack(
            sd, moe + "shared_experts.gate_proj.weight", L, transpose=True)
        blocks["sw_up"] = _stack(
            sd, moe + "shared_experts.up_proj.weight", L, transpose=True)
        blocks["sw_down"] = _stack(
            sd, moe + "shared_experts.down_proj.weight", L, transpose=True)
    if cfg.q_lora_rank:
        blocks["wq_a"] = _stack(sd, attn + "q_a_proj.weight", L, transpose=True)
        blocks["q_a_norm"] = _stack(sd, attn + "q_a_layernorm.weight", L)
        blocks["wq_b"] = _stack(sd, attn + "q_b_proj.weight", L, transpose=True)
    else:
        blocks["wq"] = _stack(sd, attn + "q_proj.weight", L, transpose=True)
    blocks.update(_qwen_moe_experts(sd, moe, L, E))
    params = {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return params



# --------------------------------------------------------------------------- #
# Phi (phi-1/1.5/2: parallel block, shared norm, partial rotary, biased head)
# --------------------------------------------------------------------------- #

def config_from_phi(hf_config) -> TransformerConfig:
    head_dim = hf_config.hidden_size // hf_config.num_attention_heads
    return TransformerConfig(
        rope_scaling=_canon_rope_scaling(hf_config),
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="layernorm", activation="gelu",
        use_bias=True, parallel_block=True, shared_parallel_norm=True,
        rope_fraction=float(getattr(hf_config, "partial_rotary_factor", 0.5)),
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        tie_embeddings=False, lm_head_bias=True,
        norm_eps=hf_config.layer_norm_eps, dtype="float32")


def params_from_phi(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L),
                "bias": _stack(sd, lyr + "input_layernorm.bias", L)},
        "wq": _stack(sd, lyr + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, lyr + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, lyr + "self_attn.v_proj.weight", L, transpose=True),
        "bq": _stack(sd, lyr + "self_attn.q_proj.bias", L),
        "bk": _stack(sd, lyr + "self_attn.k_proj.bias", L),
        "bv": _stack(sd, lyr + "self_attn.v_proj.bias", L),
        "wo": _stack(sd, lyr + "self_attn.dense.weight", L, transpose=True),
        "bo": _stack(sd, lyr + "self_attn.dense.bias", L),
        "w_up": _stack(sd, lyr + "mlp.fc1.weight", L, transpose=True),
        "b_up": _stack(sd, lyr + "mlp.fc1.bias", L),
        "w_down": _stack(sd, lyr + "mlp.fc2.weight", L, transpose=True),
        "b_down": _stack(sd, lyr + "mlp.fc2.bias", L),
    }
    return {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "final_layernorm.weight"]),
                       "bias": _np(sd[pre + "final_layernorm.bias"])},
        "lm_head": _np(sd["lm_head.weight"]).T,
        "lm_head_b": _np(sd["lm_head.bias"]),
    }


# --------------------------------------------------------------------------- #
# Phi-3 (Llama schema with fused qkv_proj / gate_up_proj)
# --------------------------------------------------------------------------- #

def config_from_phi3(hf_config) -> TransformerConfig:
    return TransformerConfig(
        rope_scaling=_canon_rope_scaling(hf_config),
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="rmsnorm", activation="swiglu", use_bias=False,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        norm_eps=hf_config.rms_norm_eps, dtype="float32")


def params_from_phi3(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "model." if any(k.startswith("model.") for k in sd) else ""
    lyr = pre + "layers.{}."
    qdim = cfg.num_heads * cfg.head_dim
    kvdim = cfg.kv_heads * cfg.head_dim
    f = cfg.ffn_size

    qkv = _stack(sd, lyr + "self_attn.qkv_proj.weight", L, transpose=True)
    gate_up = _stack(sd, lyr + "mlp.gate_up_proj.weight", L, transpose=True)
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L)},
        "wq": qkv[:, :, :qdim],
        "wk": qkv[:, :, qdim:qdim + kvdim],
        "wv": qkv[:, :, qdim + kvdim:],
        "wo": _stack(sd, lyr + "self_attn.o_proj.weight", L, transpose=True),
        "w_gate": gate_up[:, :, :f],
        "w_up": gate_up[:, :, f:],
        "w_down": _stack(sd, lyr + "mlp.down_proj.weight", L, transpose=True),
    }
    params = {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "norm.weight"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return params


# --------------------------------------------------------------------------- #
# Falcon (fused grouped QKV, parallel block; 7B = MQA + shared norm)
# --------------------------------------------------------------------------- #

def config_from_falcon(hf_config) -> TransformerConfig:
    n_head = hf_config.num_attention_heads
    if getattr(hf_config, "new_decoder_architecture", False):
        n_kv = hf_config.num_kv_heads
        parallel, shared = True, False   # ln_attn + ln_mlp (dual parallel norms)
    else:
        n_kv = 1 if getattr(hf_config, "multi_query", True) else n_head
        # parallel_attn=True → one norm feeds both branches; False → a plain
        # sequential block (falcon-rw)
        parallel = bool(getattr(hf_config, "parallel_attn", True))
        shared = parallel
    return TransformerConfig(
        rope_scaling=_canon_rope_scaling(hf_config),
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=n_head,
        num_kv_heads=n_kv,
        max_seq_len=getattr(hf_config, "max_position_embeddings", 2048),
        pos_emb="alibi" if getattr(hf_config, "alibi", False) else "rope",
        # HF Falcon adds the alibi tensor with beta=inv_norm_factor — the bias
        # rides inside the 1/sqrt(d) scaling (unlike BLOOM's beta=1)
        alibi_bias_scale=1.0 / (hf_config.hidden_size
                                // hf_config.num_attention_heads) ** 0.5,
        norm="layernorm", activation="gelu",
        use_bias=bool(getattr(hf_config, "bias", False)),
        parallel_block=parallel, shared_parallel_norm=shared,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        tie_embeddings=True,
        norm_eps=hf_config.layer_norm_epsilon, dtype="float32")


def _split_falcon_qkv(w: np.ndarray, cfg: TransformerConfig):
    """Falcon fused query_key_value [out, in] → wq/wk/wv in [in, out] layout.

    Rows are grouped as [n_kv groups × (q_per_group q-heads, 1 k, 1 v)]."""
    h, d = cfg.hidden_size, cfg.head_dim
    n_kv = cfg.kv_heads
    q_per = cfg.num_heads // n_kv
    grouped = w.reshape(n_kv, (q_per + 2) * d, h)
    q = grouped[:, : q_per * d].reshape(n_kv * q_per * d, h)
    k = grouped[:, q_per * d: (q_per + 1) * d].reshape(n_kv * d, h)
    v = grouped[:, (q_per + 1) * d:].reshape(n_kv * d, h)
    return q.T, k.T, v.T


def params_from_falcon(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    lyr = pre + "h.{}."

    wq, wk, wv = [], [], []
    for i in range(L):
        q, k, v = _split_falcon_qkv(
            _np(sd[lyr.format(i) + "self_attention.query_key_value.weight"]), cfg)
        wq.append(q); wk.append(k); wv.append(v)

    if cfg.parallel_block and not cfg.shared_parallel_norm:
        # new decoder architecture: dual parallel norms
        blocks = {
            "ln1": {"scale": _stack(sd, lyr + "ln_attn.weight", L),
                    "bias": _stack(sd, lyr + "ln_attn.bias", L)},
            "ln2": {"scale": _stack(sd, lyr + "ln_mlp.weight", L),
                    "bias": _stack(sd, lyr + "ln_mlp.bias", L)},
        }
    elif cfg.parallel_block:
        # old arch, parallel_attn: one norm feeds both branches
        blocks = {"ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L),
                          "bias": _stack(sd, lyr + "input_layernorm.bias", L)}}
    else:
        # falcon-rw: plain sequential block
        blocks = {
            "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L),
                    "bias": _stack(sd, lyr + "input_layernorm.bias", L)},
            "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L),
                    "bias": _stack(sd, lyr + "post_attention_layernorm.bias", L)},
        }
    blocks.update({
        "wq": np.stack(wq), "wk": np.stack(wk), "wv": np.stack(wv),
        "wo": _stack(sd, lyr + "self_attention.dense.weight", L, transpose=True),
        "w_up": _stack(sd, lyr + "mlp.dense_h_to_4h.weight", L, transpose=True),
        "w_down": _stack(sd, lyr + "mlp.dense_4h_to_h.weight", L, transpose=True),
    })
    return {
        "tok_emb": _np(sd[pre + "word_embeddings.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "ln_f.weight"]),
                       "bias": _np(sd[pre + "ln_f.bias"])},
    }


# --------------------------------------------------------------------------- #
# OPT (learned positions with offset 2, ReLU)
# --------------------------------------------------------------------------- #

def config_from_opt(hf_config) -> TransformerConfig:
    if hf_config.word_embed_proj_dim != hf_config.hidden_size:
        raise ValueError("OPT word_embed_proj_dim != hidden_size (350m-style "
                         "projection) is not supported")
    if not getattr(hf_config, "do_layer_norm_before", True):
        raise ValueError("OPT with do_layer_norm_before=False is not supported")
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        ffn_hidden_size=hf_config.ffn_dim,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="learned", norm="layernorm",
        activation="relu" if hf_config.activation_function == "relu" else "gelu",
        use_bias=True, tie_embeddings=True,
        norm_eps=1e-5, dtype="float32")


def params_from_opt(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L = cfg.num_layers
    pre = "model.decoder." if any(k.startswith("model.decoder.") for k in sd) \
        else "decoder." if any(k.startswith("decoder.") for k in sd) else ""
    lyr = pre + "layers.{}."
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "self_attn_layer_norm.weight", L),
                "bias": _stack(sd, lyr + "self_attn_layer_norm.bias", L)},
        "ln2": {"scale": _stack(sd, lyr + "final_layer_norm.weight", L),
                "bias": _stack(sd, lyr + "final_layer_norm.bias", L)},
        "wq": _stack(sd, lyr + "self_attn.q_proj.weight", L, transpose=True),
        "wk": _stack(sd, lyr + "self_attn.k_proj.weight", L, transpose=True),
        "wv": _stack(sd, lyr + "self_attn.v_proj.weight", L, transpose=True),
        "bq": _stack(sd, lyr + "self_attn.q_proj.bias", L),
        "bk": _stack(sd, lyr + "self_attn.k_proj.bias", L),
        "bv": _stack(sd, lyr + "self_attn.v_proj.bias", L),
        "wo": _stack(sd, lyr + "self_attn.out_proj.weight", L, transpose=True),
        "bo": _stack(sd, lyr + "self_attn.out_proj.bias", L),
        "w_up": _stack(sd, lyr + "fc1.weight", L, transpose=True),
        "b_up": _stack(sd, lyr + "fc1.bias", L),
        "w_down": _stack(sd, lyr + "fc2.weight", L, transpose=True),
        "b_down": _stack(sd, lyr + "fc2.bias", L),
    }
    return {
        "tok_emb": _np(sd[pre + "embed_tokens.weight"]),
        # HF OPT offsets positions by 2 (pad-token legacy) — drop those rows
        "pos_emb": _np(sd[pre + "embed_positions.weight"])[2:],
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "final_layer_norm.weight"]),
                       "bias": _np(sd[pre + "final_layer_norm.bias"])},
    }


# --------------------------------------------------------------------------- #
# BLOOM (ALiBi, embedding layernorm, per-head-interleaved fused QKV)
# --------------------------------------------------------------------------- #

def config_from_bloom(hf_config) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        max_seq_len=getattr(hf_config, "seq_length", 2048),
        pos_emb="alibi", norm="layernorm", activation="gelu",
        use_bias=True, emb_norm=True, tie_embeddings=True,
        norm_eps=hf_config.layer_norm_epsilon, dtype="float32")


def params_from_bloom(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, h, d = cfg.num_layers, cfg.hidden_size, cfg.head_dim
    n = cfg.num_heads
    pre = "transformer." if any(k.startswith("transformer.") for k in sd) else ""
    lyr = pre + "h.{}."

    # fused QKV rows are interleaved per head: [n_head, 3, head_dim, hidden]
    def split_qkv(i):
        w = _np(sd[lyr.format(i) + "self_attention.query_key_value.weight"])
        b = _np(sd[lyr.format(i) + "self_attention.query_key_value.bias"])
        w = w.reshape(n, 3, d, h)
        b = b.reshape(n, 3, d)
        return (w[:, 0].reshape(n * d, h).T, w[:, 1].reshape(n * d, h).T,
                w[:, 2].reshape(n * d, h).T,
                b[:, 0].reshape(-1), b[:, 1].reshape(-1), b[:, 2].reshape(-1))

    parts = [split_qkv(i) for i in range(L)]
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L),
                "bias": _stack(sd, lyr + "input_layernorm.bias", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L),
                "bias": _stack(sd, lyr + "post_attention_layernorm.bias", L)},
        "wq": np.stack([p[0] for p in parts]),
        "wk": np.stack([p[1] for p in parts]),
        "wv": np.stack([p[2] for p in parts]),
        "bq": np.stack([p[3] for p in parts]),
        "bk": np.stack([p[4] for p in parts]),
        "bv": np.stack([p[5] for p in parts]),
        "wo": _stack(sd, lyr + "self_attention.dense.weight", L, transpose=True),
        "bo": _stack(sd, lyr + "self_attention.dense.bias", L),
        "w_up": _stack(sd, lyr + "mlp.dense_h_to_4h.weight", L, transpose=True),
        "b_up": _stack(sd, lyr + "mlp.dense_h_to_4h.bias", L),
        "w_down": _stack(sd, lyr + "mlp.dense_4h_to_h.weight", L, transpose=True),
        "b_down": _stack(sd, lyr + "mlp.dense_4h_to_h.bias", L),
    }
    return {
        "tok_emb": _np(sd[pre + "word_embeddings.weight"]),
        "emb_norm": {"scale": _np(sd[pre + "word_embeddings_layernorm.weight"]),
                     "bias": _np(sd[pre + "word_embeddings_layernorm.bias"])},
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "ln_f.weight"]),
                       "bias": _np(sd[pre + "ln_f.bias"])},
    }


# --------------------------------------------------------------------------- #
# GPT-NeoX / Pythia (parallel dual-norm block, partial rotary, fused QKV)
# --------------------------------------------------------------------------- #

def config_from_gpt_neox(hf_config) -> TransformerConfig:
    return TransformerConfig(
        rope_scaling=_canon_rope_scaling(hf_config),
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        ffn_hidden_size=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        pos_emb="rope", norm="layernorm", activation="gelu",
        use_bias=True,
        parallel_block=bool(getattr(hf_config, "use_parallel_residual", True)),
        rope_fraction=float(getattr(hf_config, "rotary_pct", 0.25)),
        rope_theta=float(getattr(hf_config, "rotary_emb_base", 10000.0)),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        norm_eps=hf_config.layer_norm_eps, dtype="float32")


def params_from_gpt_neox(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    L, h, d, n = cfg.num_layers, cfg.hidden_size, cfg.head_dim, cfg.num_heads
    pre = "gpt_neox." if any(k.startswith("gpt_neox.") for k in sd) else ""
    lyr = pre + "layers.{}."

    # fused QKV interleaved per head, like BLOOM: [n_head, 3, head_dim, hidden]
    def split_qkv(i):
        w = _np(sd[lyr.format(i) + "attention.query_key_value.weight"])
        b = _np(sd[lyr.format(i) + "attention.query_key_value.bias"])
        w = w.reshape(n, 3, d, h)
        b = b.reshape(n, 3, d)
        return (w[:, 0].reshape(n * d, h).T, w[:, 1].reshape(n * d, h).T,
                w[:, 2].reshape(n * d, h).T,
                b[:, 0].reshape(-1), b[:, 1].reshape(-1), b[:, 2].reshape(-1))

    parts = [split_qkv(i) for i in range(L)]
    blocks = {
        "ln1": {"scale": _stack(sd, lyr + "input_layernorm.weight", L),
                "bias": _stack(sd, lyr + "input_layernorm.bias", L)},
        "ln2": {"scale": _stack(sd, lyr + "post_attention_layernorm.weight", L),
                "bias": _stack(sd, lyr + "post_attention_layernorm.bias", L)},
        "wq": np.stack([p[0] for p in parts]),
        "wk": np.stack([p[1] for p in parts]),
        "wv": np.stack([p[2] for p in parts]),
        "bq": np.stack([p[3] for p in parts]),
        "bk": np.stack([p[4] for p in parts]),
        "bv": np.stack([p[5] for p in parts]),
        "wo": _stack(sd, lyr + "attention.dense.weight", L, transpose=True),
        "bo": _stack(sd, lyr + "attention.dense.bias", L),
        "w_up": _stack(sd, lyr + "mlp.dense_h_to_4h.weight", L, transpose=True),
        "b_up": _stack(sd, lyr + "mlp.dense_h_to_4h.bias", L),
        "w_down": _stack(sd, lyr + "mlp.dense_4h_to_h.weight", L, transpose=True),
        "b_down": _stack(sd, lyr + "mlp.dense_4h_to_h.bias", L),
    }
    params = {
        "tok_emb": _np(sd[pre + "embed_in.weight"]),
        "blocks": blocks,
        "final_norm": {"scale": _np(sd[pre + "final_layer_norm.weight"]),
                       "bias": _np(sd[pre + "final_layer_norm.bias"])},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _np(sd["embed_out.weight"]).T
    return params


# --------------------------------------------------------------------------- #
# front door
# --------------------------------------------------------------------------- #

def config_from_exaone(hf_config) -> TransformerConfig:
    """EXAONE-3.x (model_type 'exaone'): the Llama recipe under EXAONE's own
    attribute names — alias them and delegate (reference serves the family
    via inference-v2 model_implementations; v4's post-norm block is a
    different architecture and is refused rather than silently
    mis-imported)."""
    from types import SimpleNamespace

    attrs = dict(vars(hf_config))
    attrs["num_hidden_layers"] = getattr(
        hf_config, "num_layers", getattr(hf_config, "num_hidden_layers",
                                         None))
    attrs["rms_norm_eps"] = float(
        getattr(hf_config, "layer_norm_epsilon",
                getattr(hf_config, "rms_norm_eps", 1e-5)))
    return config_from_llama(SimpleNamespace(**attrs))


def params_from_exaone(sd: Dict[str, Any], cfg: TransformerConfig) -> PyTree:
    """Rename EXAONE-3 keys (transformer.h.N.attn.attention.*, mlp.c_fc_0/1,
    ln_1/ln_2, wte) onto the Llama schema and delegate."""
    ren = {
        "transformer.wte.weight": "model.embed_tokens.weight",
        "transformer.ln_f.weight": "model.norm.weight",
        ".ln_1.weight": ".input_layernorm.weight",
        ".ln_2.weight": ".post_attention_layernorm.weight",
        ".attn.attention.q_proj.": ".self_attn.q_proj.",
        ".attn.attention.k_proj.": ".self_attn.k_proj.",
        ".attn.attention.v_proj.": ".self_attn.v_proj.",
        ".attn.attention.out_proj.": ".self_attn.o_proj.",
        ".mlp.c_fc_0.": ".mlp.gate_proj.",
        ".mlp.c_fc_1.": ".mlp.up_proj.",
        ".mlp.c_proj.": ".mlp.down_proj.",
        "transformer.h.": "model.layers.",
    }
    out = {}
    for k, v in sd.items():
        nk = k
        for old, new in ren.items():
            nk = nk.replace(old, new)
        out[nk] = v
    return params_from_llama(out, cfg)


_ARCH_TABLE = {
    "gpt2": (config_from_gpt2, params_from_gpt2),
    "llama": (config_from_llama, params_from_llama),
    "exaone": (config_from_exaone, params_from_exaone),
    "mistral": (config_from_llama, params_from_llama),
    "mixtral": (config_from_mixtral, params_from_mixtral),
    "qwen2": (config_from_qwen2, params_from_qwen2),
    "qwen3": (config_from_qwen3, params_from_qwen3),
    "qwen2_moe": (config_from_qwen2_moe, params_from_qwen2_moe),
    "qwen3_moe": (config_from_qwen3_moe, params_from_qwen3_moe),
    "deepseek_v2": (config_from_deepseek_v2, params_from_deepseek),
    "deepseek_v3": (config_from_deepseek_v3, params_from_deepseek),
    "phi": (config_from_phi, params_from_phi),
    "phi3": (config_from_phi3, params_from_phi3),
    "falcon": (config_from_falcon, params_from_falcon),
    "opt": (config_from_opt, params_from_opt),
    "bloom": (config_from_bloom, params_from_bloom),
    "gpt_neox": (config_from_gpt_neox, params_from_gpt_neox),
    # qwen-1 etc. share the llama schema under other key names; pass
    # arch='llama' explicitly after renaming, or extend this table.
    # (exaone4 is POST-norm — a different block; not silently importable)
}


def import_hf_model(model, arch: Optional[str] = None
                    ) -> Tuple[TransformerConfig, PyTree]:
    """Convert a ``transformers`` model (or (state_dict, config) pair) into
    (TransformerConfig, zoo params)."""
    if isinstance(model, tuple):
        sd, hf_config = model
    else:
        sd, hf_config = model.state_dict(), model.config
    arch = arch or getattr(hf_config, "model_type", None)
    if arch not in _ARCH_TABLE:
        raise ValueError(
            f"unsupported HF architecture {arch!r}; "
            f"supported: {sorted(_ARCH_TABLE)}")
    cfg_fn, params_fn = _ARCH_TABLE[arch]
    cfg = cfg_fn(hf_config)
    return cfg, params_fn(sd, cfg)
