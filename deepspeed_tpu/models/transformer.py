"""Torch-free transformer model zoo (GPT-2 and Llama families).

Role: the reference ships no model zoo for training (users bring HF/Megatron
models; its test fixtures are ``tests/unit/simple_model.py``), but its inference
engine has per-model implementations (``inference/v2/model_implementations/``).
This framework is torch-free, so the model zoo is first-class: functional JAX
models designed for the compiler —

* **scan over layers**: per-layer params are stacked on a leading 'layers' dim and
  the forward is a ``lax.scan`` → O(1) compile time in depth, natural hook for
  pipeline sharding and per-layer remat;
* **logical sharding axes**: every param carries a tuple of logical axis names
  (`("layers", "embed", "heads")`) consumed by ``parallel/partitioning.py`` — the
  AutoTP analog;
* **pluggable attention**: the attention callable can be swapped for the Pallas
  flash kernel, Ulysses all-to-all attention, or ring attention without touching
  the model.

Numerics: matmuls in the compute dtype (bf16 by default) with fp32 softmax and
fp32 layernorm/rmsnorm accumulation — MXU-friendly per the TPU guide.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name as _ckpt_name

PyTree = Any
AttentionFn = Callable[..., jax.Array]

# remat="selective": save ONLY the named expensive-to-recompute intermediates
# (attention output, FFN activation) — residual stream + elementwise recompute
# for free, the attention kernel and the big FFN matmul never re-run in bwd.
# Storage per token per layer ≈ (heads·D + ffn) · 2 bytes, far below "none";
# recompute far below "full".
_SELECTIVE_POLICY = jax.checkpoint_policies.save_only_these_names(
    "attn_out", "ffn_act", "moe_gate")

# "moe_selective": selective + the expert grouped-GEMM intermediates
# (moe_up/moe_act, named in moe.layer.ragged_expert_ffn) — backward then
# re-runs NO ragged dots, trading ~200 MB/layer of bf16 residuals for ~25%
# of the expert FLOPs per step. The right default for MoE models where the
# experts dominate FLOPs; dense models save nothing extra under it.
_MOE_SELECTIVE_POLICY = jax.checkpoint_policies.save_only_these_names(
    "attn_out", "ffn_act", "moe_gate", "moe_up", "moe_act")


def _remat_wrap(body, remat: str):
    """Apply the layer-scan remat policy; unknown names raise (a typo must
    not silently disable remat)."""
    if remat in ("none", None):
        return body
    if remat in ("full", "save_nothing"):
        return jax.checkpoint(body)
    if remat == "dots_saveable":
        return jax.checkpoint(body, policy=jax.checkpoint_policies.dots_saveable)
    if remat == "dots_no_batch":
        # save every WEIGHT-matmul output (qkv/attn-proj/ffn projections —
        # "dots with no batch dims"); bwd then re-runs only norms,
        # elementwise and the attention einsums. Cuts nearly all of
        # remat="full"'s ~25% recompute FLOPs at bf16-activation storage
        # cost, without dots_saveable's fp32 attention-score traffic.
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    if remat in ("attn_block", "ffn_block"):
        # structural sub-block checkpoint — applied INSIDE _block_forward
        # around one sub-block; the scan body itself is not rematted, so
        # the other sub-block's activations are saved by ordinary AD and
        # XLA's scan fusion stays intact (the names-policy selective remat
        # measurably disrupts it, PROFILE.md round-2 sweep)
        return body
    if remat == "selective":
        return jax.checkpoint(body, policy=_SELECTIVE_POLICY)
    if remat == "moe_selective":
        return jax.checkpoint(body, policy=_MOE_SELECTIVE_POLICY)
    if remat == "offload_dots":
        # ActivationCheckpointingConfig.policy="offload_dots": the selective
        # saves live in pinned host memory instead of HBM
        policy = jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=["moe_gate"],  # tiny dispatch indices
            names_which_can_be_offloaded=["attn_out", "ffn_act"],
            offload_src="device", offload_dst="pinned_host")
        return jax.checkpoint(body, policy=policy)
    raise ValueError(
        f"unknown remat policy {remat!r}; one of none|full|save_nothing|"
        "dots_saveable|dots_no_batch|selective|moe_selective|offload_dots|"
        "attn_block|ffn_block")


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # None → MHA; < num_heads → GQA
    ffn_hidden_size: Optional[int] = None
    max_seq_len: int = 1024
    pos_emb: str = "learned"            # learned | rope | alibi | none
    norm: str = "layernorm"             # layernorm | rmsnorm
    activation: str = "gelu"            # gelu | swiglu | relu
    use_bias: bool = True
    qkv_bias: bool = False              # bias on q/k/v only (Qwen2-style)
    parallel_block: bool = False        # attn + FFN in parallel (Falcon/NeoX/Phi)
    shared_parallel_norm: bool = False  # parallel block, ONE norm feeds both
                                        # branches (Falcon new-arch, Phi)
    emb_norm: bool = False              # layernorm after embedding (BLOOM)
    alibi_bias_scale: float = 1.0       # Falcon folds 1/sqrt(d) into the bias
    lm_head_bias: bool = False          # bias on the LM head (Phi)
    rope_fraction: float = 1.0          # partial rotary (NeoX 0.25, Phi-2 0.4)
    tie_embeddings: bool = True
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    init_std: float = 0.02
    dtype: str = "bfloat16"             # compute dtype
    remat: str = "none"   # none | full (= save_nothing) | dots_saveable |
    #                         selective (save attn_out+ffn_act) |
    #                         offload_dots (selective saves live on pinned host)
    causal: bool = True                 # False → bidirectional encoder (BERT)
    # QAT activation quantization (reference compression/basic_layer.py
    # QuantAct): fake-quantize the normed hidden stream feeding each
    # block's linears (STE backward). 0 = off; set via the
    # compression_training "activation_quantization" config section.
    act_quant_bits: int = 0
    # MoE (reference deepspeed/moe/; 0 experts → dense FFN)
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_min_capacity: int = 4
    moe_aux_coef: float = 0.01
    moe_dispatch: str = "auto"  # auto | ragged (dropless) | dense (GShard)
    # MoE routing/arch variants (AutoEP presets: mixtral/qwen-moe/deepseek)
    moe_ffn_size: Optional[int] = None  # routed-expert intermediate (≠ dense ffn)
    moe_shared_size: int = 0            # shared-expert intermediate; 0 = none
    moe_shared_gate: bool = False       # sigmoid gate on shared out (Qwen2-MoE)
    moe_score_func: str = "softmax"     # softmax | sigmoid (DeepSeek-V3)
    moe_route_norm: bool = True         # renormalize top-k weights to sum 1
    moe_route_scale: float = 1.0        # routed_scaling_factor (DeepSeek)
    qk_norm: bool = False               # RMSNorm on q/k head dim (Qwen3)
    attn_head_dim: Optional[int] = None  # explicit head dim (Qwen3 ≠ H/N)
    # MLA — Multi-head Latent Attention (DeepSeek V2/V3): queries and KV are
    # projected through low-rank latents; only the latent c_kv (+ the shared
    # rope key) is cached at decode — the 93%-smaller-KV-cache trick.
    mla: bool = False
    q_lora_rank: Optional[int] = None   # None → direct q projection (V2-lite)
    kv_lora_rank: int = 0
    qk_nope_head_dim: int = 0
    qk_rope_head_dim: int = 0
    v_head_dim: int = 0
    rope_interleave: bool = True        # DeepSeek stores rope pairs interleaved
    # HF rope_scaling dict, canonicalized to a sorted tuple of items so the
    # frozen config stays hashable (None = unscaled)
    rope_scaling: Optional[Tuple[Tuple[str, Any], ...]] = None
    # DeepSeek-V3 router extras (moe/gating.py)
    moe_gate_bias: bool = False         # e_score_correction_bias parameter
    moe_n_group: int = 1                # node-limited routing groups
    moe_topk_group: int = 1
    # compute-time QKV fusion: one [H, q+k+v] matmul instead of three (the
    # reference's fused-QKV transformer kernels, csrc/transformer
    # attn_quantizer/transform kernels). Params stay separate (importers,
    # TP axes unchanged); the concat happens per layer inside the step and
    # XLA materializes it once per weight version.
    fuse_qkv: bool = False
    # overlap scheduler (parallel/overlap.py; reference stage3 prefetch +
    # IPG buckets): split the layer scan into this many sequential chunk
    # scans so ZeRO-3 gathers one chunk ahead of compute and each chunk's
    # gradient sync is final mid-backward. 0/1 = single scan (today's
    # program). Numerics are identical either way; the engine sets this
    # from stage3_prefetch_bucket_size / reduce_bucket_size.
    scan_chunks: int = 0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        if self.attn_head_dim is not None:
            return self.attn_head_dim
        return self.hidden_size // self.num_heads

    @property
    def moe_ffn(self) -> int:
        """Routed-expert intermediate size."""
        return self.moe_ffn_size if self.moe_ffn_size is not None else self.ffn_size

    @property
    def ffn_size(self) -> int:
        if self.ffn_hidden_size is not None:
            return self.ffn_hidden_size
        if self.activation == "swiglu":
            # Llama sizing: 2/3 * 4H rounded to multiple of 256
            raw = int(8 * self.hidden_size / 3)
            return 256 * ((raw + 255) // 256)
        return 4 * self.hidden_size

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def attn_bias_enabled(self) -> bool:
        return self.use_bias or self.qkv_bias

    @property
    def rope_scaling_dict(self) -> Optional[Dict[str, Any]]:
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def mla_scale_mult(self) -> float:
        """DeepSeek yarn: softmax scale gains mscale(factor, mscale_all_dim)²
        on top of the cos/sin attention factor (HF DeepseekV3Attention)."""
        sc = self.rope_scaling_dict
        if not sc or not self.mla:
            return 1.0
        mall = sc.get("mscale_all_dim", 0)
        factor = float(sc.get("factor", 1.0))
        if mall and factor > 1:
            m = 0.1 * float(mall) * math.log(factor) + 1.0
            return m * m
        return 1.0

    @property
    def rope_dim(self) -> int:
        """Rotary dims (even), = head_dim * rope_fraction."""
        d = int(self.head_dim * self.rope_fraction)
        return d - (d % 2)

    @property
    def has_ln2(self) -> bool:
        return not (self.parallel_block and self.shared_parallel_norm)

    def num_params(self) -> int:
        h, f, v, l = self.hidden_size, self.ffn_size, self.vocab_size, self.num_layers
        if self.mla:
            dn, dr, dv = (self.qk_nope_head_dim, self.qk_rope_head_dim,
                          self.v_head_dim)
            kvr, N = self.kv_lora_rank, self.num_heads
            qout = N * (dn + dr)
            if self.q_lora_rank:
                per_layer = (h * self.q_lora_rank + self.q_lora_rank
                             + self.q_lora_rank * qout)
            else:
                per_layer = h * qout
            per_layer += (h * (kvr + dr) + kvr + kvr * N * (dn + dv)
                          + N * dv * h)
        else:
            kv = self.kv_heads * self.head_dim
            qdim = self.num_heads * self.head_dim
            per_layer = h * qdim + 2 * h * kv + qdim * h  # q, k, v, o
        ffn_mats = 3 if self.activation == "swiglu" else 2
        if self.n_experts > 0:
            per_layer += self.n_experts * ffn_mats * h * self.moe_ffn + h * self.n_experts
            per_layer += ffn_mats * h * self.moe_shared_size  # shared expert
            if self.moe_shared_gate:
                per_layer += h
            if self.moe_gate_bias:
                per_layer += self.n_experts
        else:
            per_layer += ffn_mats * h * f
        per_layer += (2 * h if self.has_ln2 else h)  # norms
        if self.qk_norm:
            per_layer += 2 * self.head_dim
        total = l * per_layer + v * h + 2 * h
        if self.emb_norm:
            total += 2 * h
        if not self.tie_embeddings:
            total += v * h
        if self.pos_emb == "learned":
            total += self.max_seq_len * h
        return total


# --------------------------------------------------------------------------- #
# init
# --------------------------------------------------------------------------- #

def init_params(cfg: TransformerConfig, rng: jax.Array) -> PyTree:
    """fp32 master parameters. Output projections scaled by 1/sqrt(2L) (GPT-2)."""
    h, f, L = cfg.hidden_size, cfg.ffn_size, cfg.num_layers
    qdim = cfg.num_heads * cfg.head_dim
    kvdim = cfg.kv_heads * cfg.head_dim
    std = cfg.init_std
    out_std = std / math.sqrt(2 * L)
    keys = jax.random.split(rng, 16)

    def norm_init(shape):
        p = {"scale": jnp.ones(shape, jnp.float32)}
        if cfg.norm == "layernorm":
            p["bias"] = jnp.zeros(shape, jnp.float32)
        return p

    def dense(key, shape, s):
        return jax.random.normal(key, shape, jnp.float32) * s

    block = {"ln1": norm_init((L, h))}
    if cfg.mla:
        dn, dr, dv = (cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
                      cfg.v_head_dim)
        kvr, N = cfg.kv_lora_rank, cfg.num_heads
        qout = N * (dn + dr)
        if cfg.q_lora_rank:
            block["wq_a"] = dense(keys[0], (L, h, cfg.q_lora_rank), std)
            block["q_a_norm"] = jnp.ones((L, cfg.q_lora_rank), jnp.float32)
            block["wq_b"] = dense(keys[15], (L, cfg.q_lora_rank, qout), std)
        else:
            block["wq"] = dense(keys[0], (L, h, qout), std)
        block["wkv_a"] = dense(keys[1], (L, h, kvr + dr), std)
        block["kv_a_norm"] = jnp.ones((L, kvr), jnp.float32)
        block["wkv_b"] = dense(keys[2], (L, kvr, N * (dn + dv)), std)
        block["wo"] = dense(keys[3], (L, N * dv, h), out_std)
    else:
        block.update({
            "wq": dense(keys[0], (L, h, qdim), std),
            "wk": dense(keys[1], (L, h, kvdim), std),
            "wv": dense(keys[2], (L, h, kvdim), std),
            "wo": dense(keys[3], (L, qdim, h), out_std),
        })
    if cfg.has_ln2:
        block["ln2"] = norm_init((L, h))
    if cfg.qk_norm:
        block["q_norm"] = jnp.ones((L, cfg.head_dim), jnp.float32)
        block["k_norm"] = jnp.ones((L, cfg.head_dim), jnp.float32)
    E = cfg.n_experts
    if E > 0:
        # MoE FFN: per-expert weights (no biases), router gate per layer
        fe = cfg.moe_ffn
        block["gate_w"] = dense(keys[10], (L, h, E), std)
        block["w_up"] = dense(keys[4], (L, E, h, fe), std)
        block["w_down"] = dense(keys[5], (L, E, fe, h), out_std)
        if cfg.activation == "swiglu":
            block["w_gate"] = dense(keys[6], (L, E, h, fe), std)
        fs = cfg.moe_shared_size
        if fs > 0:
            # always-on shared expert (Qwen2-MoE/DeepSeek)
            block["sw_up"] = dense(keys[11], (L, h, fs), std)
            block["sw_down"] = dense(keys[12], (L, fs, h), out_std)
            if cfg.activation == "swiglu":
                block["sw_gate"] = dense(keys[13], (L, h, fs), std)
            if cfg.moe_shared_gate:
                block["shared_gate_w"] = dense(keys[14], (L, h, 1), std)
        if cfg.moe_gate_bias:
            block["gate_bias"] = jnp.zeros((L, E), jnp.float32)
    else:
        block["w_up"] = dense(keys[4], (L, h, f), std)
        block["w_down"] = dense(keys[5], (L, f, h), out_std)
        if cfg.activation == "swiglu":
            block["w_gate"] = dense(keys[6], (L, h, f), std)
    if cfg.attn_bias_enabled:
        block["bq"] = jnp.zeros((L, qdim), jnp.float32)
        block["bk"] = jnp.zeros((L, kvdim), jnp.float32)
        block["bv"] = jnp.zeros((L, kvdim), jnp.float32)
    if cfg.use_bias:
        block["bo"] = jnp.zeros((L, h), jnp.float32)
        if E == 0:
            block["b_up"] = jnp.zeros((L, f), jnp.float32)
            block["b_down"] = jnp.zeros((L, h), jnp.float32)

    params = {
        "tok_emb": dense(keys[7], (cfg.vocab_size, h), std),
        "blocks": block,
        "final_norm": norm_init((h,)),
    }
    if cfg.pos_emb == "learned":
        params["pos_emb"] = dense(keys[8], (cfg.max_seq_len, h), std)
    if cfg.emb_norm:
        params["emb_norm"] = norm_init((h,))
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(keys[9], (h, cfg.vocab_size), std)
        if cfg.lm_head_bias:
            params["lm_head_b"] = jnp.zeros((cfg.vocab_size,), jnp.float32)
    return params


def param_logical_axes(cfg: TransformerConfig) -> PyTree:
    """Logical axis names per parameter dim (consumed by the sharding policy)."""
    def norm_axes(prefix):
        p = {"scale": prefix + ("embed",)}
        if cfg.norm == "layernorm":
            p["bias"] = prefix + ("embed",)
        return p

    lyr = ("layers",)
    block = {"ln1": norm_axes(lyr)}
    if cfg.mla:
        # latent projections: ranks are shared (replicated); the per-head
        # output dims carry the 'heads' axis for TP
        if cfg.q_lora_rank:
            block["wq_a"] = lyr + ("embed", None)
            block["q_a_norm"] = lyr + (None,)
            block["wq_b"] = lyr + (None, "heads")
        else:
            block["wq"] = lyr + ("embed", "heads")
        block["wkv_a"] = lyr + ("embed", None)
        block["kv_a_norm"] = lyr + (None,)
        block["wkv_b"] = lyr + (None, "heads")
        block["wo"] = lyr + ("heads", "embed")
    else:
        block.update({
            "wq": lyr + ("embed", "heads"),
            "wk": lyr + ("embed", "kv_heads"),
            "wv": lyr + ("embed", "kv_heads"),
            "wo": lyr + ("heads", "embed"),
        })
    if cfg.has_ln2:
        block["ln2"] = norm_axes(lyr)
    if cfg.qk_norm:
        block["q_norm"] = lyr + (None,)
        block["k_norm"] = lyr + (None,)
    if cfg.n_experts > 0:
        block["gate_w"] = lyr + ("embed", None)
        block["w_up"] = lyr + ("expert", "embed", "mlp")
        block["w_down"] = lyr + ("expert", "mlp", "embed")
        if cfg.activation == "swiglu":
            block["w_gate"] = lyr + ("expert", "embed", "mlp")
        if cfg.moe_shared_size > 0:
            block["sw_up"] = lyr + ("embed", "mlp")
            block["sw_down"] = lyr + ("mlp", "embed")
            if cfg.activation == "swiglu":
                block["sw_gate"] = lyr + ("embed", "mlp")
            if cfg.moe_shared_gate:
                block["shared_gate_w"] = lyr + ("embed", None)
        if cfg.moe_gate_bias:
            block["gate_bias"] = lyr + (None,)
    else:
        block["w_up"] = lyr + ("embed", "mlp")
        block["w_down"] = lyr + ("mlp", "embed")
        if cfg.activation == "swiglu":
            block["w_gate"] = lyr + ("embed", "mlp")
    if cfg.attn_bias_enabled:
        block.update({
            "bq": lyr + ("heads",), "bk": lyr + ("kv_heads",),
            "bv": lyr + ("kv_heads",),
        })
    if cfg.use_bias:
        block["bo"] = lyr + ("embed",)
        if cfg.n_experts == 0:
            block.update({"b_up": lyr + ("mlp",), "b_down": lyr + ("embed",)})
    axes = {
        "tok_emb": ("vocab", "embed"),
        "blocks": block,
        "final_norm": norm_axes(()),
    }
    if cfg.pos_emb == "learned":
        axes["pos_emb"] = ("seq", "embed")
    if cfg.emb_norm:
        axes["emb_norm"] = norm_axes(())
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
        if cfg.lm_head_bias:
            axes["lm_head_b"] = ("vocab",)
    return axes


# --------------------------------------------------------------------------- #
# building blocks
# --------------------------------------------------------------------------- #

def _norm(x: jax.Array, p: Dict[str, jax.Array], kind: str, eps: float) -> jax.Array:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        out = x32 * lax.rsqrt(var + eps) * p["scale"]
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
        out = (x32 - mean) * lax.rsqrt(var + eps) * p["scale"] + p["bias"]
    return out.astype(dtype)


def _lm_head_of(params: PyTree, cfg: TransformerConfig) -> jax.Array:
    """LM head matrix [H, V]; dequantizes a weight-only-quantized head."""
    if cfg.tie_embeddings:
        return params["tok_emb"].T
    head = params["lm_head"]
    if isinstance(head, dict):
        from deepspeed_tpu.ops.quantization import dequantize_weight

        return dequantize_weight(head, cfg.compute_dtype)
    return head


def _head_rmsnorm(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    """QK-norm (Qwen3): RMSNorm over the head dim of [B,S,N,D] q/k."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * scale).astype(dtype)


def _scaled_inv_freq(head_dim: int, theta: float,
                     scaling: Optional[Dict[str, Any]]):
    """Inverse rope frequencies with HF-compatible scaling (numpy, trace-time
    constants). Supports the types real checkpoints use: 'default',
    'linear', 'llama3' (Llama-3.x piecewise wavelength scaling), 'yarn'
    (NTK interpolation/extrapolation blend + attention factor — DeepSeek,
    Qwen-long). Mirrors ``transformers/modeling_rope_utils.py``.

    → (inv_freq [D/2] np.float32, attention_factor float — multiplies the
    cos/sin tables, HF convention)."""
    import numpy as _onp

    inv = 1.0 / (theta ** (_onp.arange(0, head_dim, 2, dtype=_onp.float64)
                           / head_dim))
    if not scaling:
        return inv.astype(_onp.float32), 1.0
    sc = dict(scaling)
    rtype = sc.get("rope_type", sc.get("type", "default"))
    factor = float(sc.get("factor", 1.0))
    if rtype == "default":
        return inv.astype(_onp.float32), 1.0
    if rtype == "linear":
        return (inv / factor).astype(_onp.float32), 1.0
    if rtype == "llama3":
        low_f = float(sc["low_freq_factor"])
        high_f = float(sc["high_freq_factor"])
        old_ctx = float(sc["original_max_position_embeddings"])
        wavelen = 2 * math.pi / inv
        out = _onp.where(wavelen > old_ctx / low_f, inv / factor, inv)
        smooth = (old_ctx / wavelen - low_f) / (high_f - low_f)
        smoothed = (1 - smooth) * out / factor + smooth * out
        medium = (wavelen >= old_ctx / high_f) & (wavelen <= old_ctx / low_f)
        out = _onp.where(medium, smoothed, out)
        return out.astype(_onp.float32), 1.0
    if rtype == "yarn":
        d2 = head_dim // 2
        old_ctx = float(sc.get("original_max_position_embeddings") or 0) or None
        max_pos = old_ctx if old_ctx else float(sc.get("max_position_embeddings", 2048))
        mscale = sc.get("mscale")
        mscale_all = sc.get("mscale_all_dim")

        def get_mscale(scale, m=1.0):
            return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

        att = sc.get("attention_factor")
        if att is None:
            if mscale and mscale_all:
                att = get_mscale(factor, mscale) / get_mscale(factor, mscale_all)
            else:
                att = get_mscale(factor)
        beta_fast = float(sc.get("beta_fast") or 32)
        beta_slow = float(sc.get("beta_slow") or 1)

        def corr_dim(rot):
            return (head_dim * math.log(max_pos / (rot * 2 * math.pi))
                    ) / (2 * math.log(theta))

        low = max(math.floor(corr_dim(beta_fast)), 0)
        high = min(math.ceil(corr_dim(beta_slow)), head_dim - 1)
        if low == high:
            high += 0.001
        ramp = _onp.clip((_onp.arange(d2, dtype=_onp.float64) - low)
                         / (high - low), 0, 1)
        extrap_mask = 1 - ramp
        out = (inv / factor) * (1 - extrap_mask) + inv * extrap_mask
        return out.astype(_onp.float32), float(att)
    raise NotImplementedError(
        f"rope_scaling type {rtype!r} is not implemented "
        "(supported: default, linear, llama3, yarn)")


def rope_table(seq_len: int, head_dim: int, theta: float,
               scaling: Optional[Dict[str, Any]] = None
               ) -> Tuple[jax.Array, jax.Array]:
    inv_freq, att = _scaled_inv_freq(head_dim, theta, scaling)
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, jnp.asarray(inv_freq))          # [S, D/2]
    return jnp.cos(freqs) * att, jnp.sin(freqs) * att


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, N, D]; rotates pairs (interleaved halves convention).
    When the tables cover fewer dims than D (partial rotary, NeoX/Phi), the
    trailing dims pass through unrotated."""
    rot = 2 * cos.shape[-1]
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    d2 = rot // 2
    x1, x2 = x_rot[..., :d2], x_rot[..., d2:]
    cos = cos[None, :, None, :].astype(x.dtype)
    sin = sin[None, :, None, :].astype(x.dtype)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out


def alibi_slopes(n_heads: int) -> jax.Array:
    """ALiBi per-head slopes (BLOOM/press-et-al formula, incl. non-pow2)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        sl = pow2_slopes(n_heads)
    else:
        base = 2 ** math.floor(math.log2(n_heads))
        sl = pow2_slopes(base)
        extra = pow2_slopes(2 * base)[0::2][: n_heads - base]
        sl = sl + extra
    return jnp.asarray(sl, jnp.float32)


def alibi_bias(n_heads: int, seq_len: int) -> jax.Array:
    """[N, S, S] additive attention bias: slope * (key_pos - query_pos)."""
    slopes = alibi_slopes(n_heads)
    rel = (jnp.arange(seq_len)[None, :] - jnp.arange(seq_len)[:, None])
    return slopes[:, None, None] * rel[None].astype(jnp.float32)


@jax.custom_vjp
def head_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """LM-head projection: MXU-speed matmul with fp32 accumulation.

    ``x @ w`` with inputs kept in the compute dtype (bf16 → MXU) and the
    product accumulated/returned in fp32. The custom VJP casts the fp32
    cotangent back to the compute dtype so BOTH backward matmuls also hit the
    MXU — naive fp32 upcasting makes the vocab projection (the largest matmul
    in small/mid LMs) run at the ~8×-slower fp32 rate on TPU in fwd and bwd.
    """
    return jnp.matmul(x, w, preferred_element_type=jnp.float32)


def _head_matmul_fwd(x, w):
    return head_matmul(x, w), (x, w)


def _head_matmul_bwd(res, g):
    x, w = res
    gl = g.astype(x.dtype)
    dx = jnp.matmul(gl, w.T, preferred_element_type=jnp.float32).astype(x.dtype)
    x2 = x.reshape(-1, x.shape[-1])
    g2 = gl.reshape(-1, gl.shape[-1])
    dw = jnp.matmul(x2.T, g2, preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


head_matmul.defvjp(_head_matmul_fwd, _head_matmul_bwd)


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          causal: bool = True,
                          segment_mask: Optional[jax.Array] = None,
                          bias: Optional[jax.Array] = None) -> jax.Array:
    """Reference (XLA-fused) attention. q:[B,S,N,D] k,v:[B,S,K,D]. fp32 softmax.
    ``bias``: additive [N, S, S] (ALiBi) applied before masking."""
    B, S, N, D = q.shape
    K = k.shape[2]
    if K != N:
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bsnd,btnd->bnst", q, k).astype(jnp.float32) * scale
    if bias is not None:
        scores = scores + bias[None]
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        scores = jnp.where(mask[None, None], scores, -1e30)
    if segment_mask is not None:
        scores = jnp.where(segment_mask[:, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def _rope_deinterleave(x: jax.Array) -> jax.Array:
    """DeepSeek stores rope dims as interleaved (re,im) pairs; permute to the
    half-split layout rotate_half rope expects (HF
    ``apply_rotary_pos_emb_interleave``)."""
    *lead, d = x.shape
    return x.reshape(*lead, d // 2, 2).swapaxes(-1, -2).reshape(*lead, d)


def _mla_q(h: jax.Array, lp: Dict[str, jax.Array], cfg: TransformerConfig,
           rope_fn) -> jax.Array:
    """MLA query path: (optional) low-rank q projection + decoupled rope on
    the pe dims → [B, S, N, dn+dr] (HF ``DeepseekV3Attention.forward``)."""
    B, S, _ = h.shape
    dt = h.dtype
    dn, dr = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    if cfg.q_lora_rank:
        qa = h @ lp["wq_a"].astype(dt)
        qa = _head_rmsnorm(qa, lp["q_a_norm"], cfg.norm_eps)
        q = qa @ lp["wq_b"].astype(dt)
    else:
        q = h @ lp["wq"].astype(dt)
    q = q.reshape(B, S, cfg.num_heads, dn + dr)
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    if cfg.rope_interleave:
        q_pe = _rope_deinterleave(q_pe)
    return jnp.concatenate([q_nope, rope_fn(q_pe)], axis=-1)


def _mla_latents(h: jax.Array, lp: Dict[str, jax.Array],
                 cfg: TransformerConfig, rope_fn
                 ) -> Tuple[jax.Array, jax.Array]:
    """MLA KV latents: normed c_kv [B, S, kvr] + post-rope shared key
    [B, S, 1, dr] — exactly what the decode path caches."""
    dt = h.dtype
    kvr = cfg.kv_lora_rank
    kv_a = h @ lp["wkv_a"].astype(dt)                 # [B, S, kvr+dr]
    c_kv = _head_rmsnorm(kv_a[..., :kvr], lp["kv_a_norm"], cfg.norm_eps)
    k_pe = kv_a[..., kvr:][:, :, None, :]             # [B, S, 1, dr] shared
    if cfg.rope_interleave:
        k_pe = _rope_deinterleave(k_pe)
    return c_kv, rope_fn(k_pe)


def _mla_expand(c_kv: jax.Array, k_pe: jax.Array,
                lp: Dict[str, jax.Array], cfg: TransformerConfig
                ) -> Tuple[jax.Array, jax.Array]:
    """Latents → full per-head k [B, S, N, dn+dr] and v [B, S, N, dv]."""
    dt = c_kv.dtype
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    B, S = c_kv.shape[:2]
    N = cfg.num_heads
    kv = (c_kv @ lp["wkv_b"].astype(dt)).reshape(B, S, N, dn + dv)
    k = jnp.concatenate(
        [kv[..., :dn], jnp.broadcast_to(k_pe, (B, S, N, dr))], axis=-1)
    return k, kv[..., dn:]


def _mla_qkv(h: jax.Array, lp: Dict[str, jax.Array], cfg: TransformerConfig,
             rope_fn) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Full MLA projections for the training/prefill path."""
    q = _mla_q(h, lp, cfg, rope_fn)
    c_kv, k_pe = _mla_latents(h, lp, cfg, rope_fn)
    k, v = _mla_expand(c_kv, k_pe, lp, cfg)
    return q, k, v


def _mla_absorbed_attention(q: jax.Array, ckv: jax.Array, kpe: jax.Array,
                            lp: Dict[str, jax.Array], cfg: TransformerConfig,
                            positions: jax.Array, scale_mult: float
                            ) -> jax.Array:
    """Weight-absorbed MLA decode (the DeepSeek inference trick): fold
    W_uk into the query and W_uv into the output so attention runs ENTIRELY
    in the latent space — per step the cache is read once at width kvr+dr
    and the O(M·N·(dn+dv)) k/v re-expansion never happens.

    q: [B,T,N,dn+dr] (post-rope); ckv: [B,M,kvr] (normed latents);
    kpe: [B,M,dr] (post-rope shared key); → [B,T,N,dv].
    """
    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    kvr, N = cfg.kv_lora_rank, cfg.num_heads
    B, T = q.shape[:2]
    M = ckv.shape[1]
    dt = q.dtype
    w_kv = lp["wkv_b"].astype(dt).reshape(kvr, N, dn + dv)
    w_uk, w_uv = w_kv[..., :dn], w_kv[..., dn:]          # [kvr, N, dn/dv]
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    # absorb: q ↦ latent space (per head)
    q_lat = jnp.einsum("btnd,knd->btnk", q_nope, w_uk)   # [B,T,N,kvr]
    scale = scale_mult / math.sqrt(dn + dr)
    scores = (jnp.einsum("btnk,bmk->bntm", q_lat, ckv)
              + jnp.einsum("btnr,bmr->bntm", q_pe, kpe)
              ).astype(jnp.float32) * scale
    mask = jnp.arange(M)[None, None, None, :] <= positions[:, None, :, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(dt)
    out_lat = jnp.einsum("bntm,bmk->btnk", probs, ckv)   # [B,T,N,kvr]
    return jnp.einsum("btnk,knd->btnd", out_lat, w_uv)   # [B,T,N,dv]


def _block_forward(x: jax.Array, lp: Dict[str, jax.Array], cfg: TransformerConfig,
                   cos: Optional[jax.Array], sin: Optional[jax.Array],
                   attention_fn: AttentionFn) -> Tuple[jax.Array, jax.Array]:
    """One transformer block; lp holds this layer's (unstacked) params.
    Returns (output, moe aux loss — 0.0 for dense blocks).

    Sequential (GPT/Llama) or parallel (Falcon/NeoX/Phi: attn and FFN both
    branch off the residual stream and are summed back).

    Weight-only-quantized params ({"q","scale","zero"} subtrees —
    ``ops/quantization.py weight_quantize_groupwise``) dequantize HERE, per
    layer inside the scan: at most one layer of fp weights is live."""
    from deepspeed_tpu.ops.quantization import dequant_params

    B, S, H = x.shape
    dt = cfg.compute_dtype
    lp = dequant_params(lp, dt)

    def proj(name, inp, shape):
        w = lp[f"w{name}"].astype(dt)
        out = inp @ w
        if (cfg.attn_bias_enabled if name in ("q", "k", "v") else cfg.use_bias):
            out = out + lp[f"b{name}"].astype(dt)
        return out.reshape(shape)

    structural = cfg.remat in ("attn_block", "ffn_block")
    if structural and (cfg.mla or cfg.parallel_block):
        raise ValueError(
            f"remat={cfg.remat!r} (structural sub-block checkpoint) supports "
            "the sequential non-MLA block only; use full/selective for "
            "MLA/parallel-block models")

    def _aq(h):
        # QAT activation fake-quant on the linears' inputs (QuantAct
        # placement: after the norm, before every projection); STE backward
        if not cfg.act_quant_bits:
            return h
        from deepspeed_tpu.compression.quantize import fake_quant_symmetric

        return fake_quant_symmetric(
            h, float(2 ** (cfg.act_quant_bits - 1) - 1))

    h = _aq(_norm(x, lp["ln1"], cfg.norm, cfg.norm_eps))
    if cfg.mla:
        q, k, v = _mla_qkv(h, lp, cfg,
                           lambda t: apply_rope(t, cos, sin))
        if cfg.mla_scale_mult != 1.0:
            q = q * jnp.asarray(cfg.mla_scale_mult, q.dtype)
        # flash kernels assume one head dim; MLA's split qk/v dims run on
        # the XLA reference attention (scale = 1/sqrt(dn+dr) from q's D)
        attn = dot_product_attention(q, k, v, causal=cfg.causal)
        attn = attn.reshape(B, S, cfg.num_heads * cfg.v_head_dim)
        attn = _ckpt_name(attn, "attn_out")
        attn_out = attn @ lp["wo"].astype(dt)
        x = x + attn_out
        h2 = _aq(_norm(x, lp["ln2"], cfg.norm, cfg.norm_eps))
        down, aux = _ffn(h2, lp, cfg)
        return x + down, aux
    def _attn_from_norm(h):
        if cfg.fuse_qkv:
            qdim = cfg.num_heads * cfg.head_dim
            kvdim = cfg.kv_heads * cfg.head_dim
            wqkv = jnp.concatenate(
                [lp["wq"].astype(dt), lp["wk"].astype(dt), lp["wv"].astype(dt)],
                axis=-1)
            qkv = h @ wqkv
            if cfg.attn_bias_enabled:
                qkv = qkv + jnp.concatenate(
                    [lp["bq"], lp["bk"], lp["bv"]], axis=-1).astype(dt)
            q = qkv[..., :qdim].reshape(B, S, cfg.num_heads, cfg.head_dim)
            k = qkv[..., qdim:qdim + kvdim].reshape(
                B, S, cfg.kv_heads, cfg.head_dim)
            v = qkv[..., qdim + kvdim:].reshape(
                B, S, cfg.kv_heads, cfg.head_dim)
        else:
            q = proj("q", h, (B, S, cfg.num_heads, cfg.head_dim))
            k = proj("k", h, (B, S, cfg.kv_heads, cfg.head_dim))
            v = proj("v", h, (B, S, cfg.kv_heads, cfg.head_dim))
        if cfg.qk_norm:
            q = _head_rmsnorm(q, lp["q_norm"], cfg.norm_eps)
            k = _head_rmsnorm(k, lp["k_norm"], cfg.norm_eps)
        if cfg.pos_emb == "rope":
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        attn_kwargs = {}
        if cfg.pos_emb == "alibi":
            attn_kwargs["bias"] = \
                alibi_bias(cfg.num_heads, S) * cfg.alibi_bias_scale
        attn = attention_fn(q, k, v, causal=cfg.causal, **attn_kwargs)
        attn = attn.reshape(B, S, cfg.num_heads * cfg.head_dim)
        attn = _ckpt_name(attn, "attn_out")
        attn_out = attn @ lp["wo"].astype(dt)
        if cfg.use_bias:
            attn_out = attn_out + lp["bo"].astype(dt)
        return attn_out

    if cfg.remat == "attn_block":
        # structural remat: bwd recomputes ONLY norm1 → attention → wo
        # (~37% of layer FLOPs at 4h² vs FFN's 8h²); every FFN intermediate
        # stays saved by the scan's AD — no names policy, so XLA's scan
        # fusion is untouched. Memory ≈ 10·B·S·H bf16 per layer.
        attn_out = jax.checkpoint(
            lambda xin: _attn_from_norm(
                _aq(_norm(xin, lp["ln1"], cfg.norm, cfg.norm_eps))))(x)
    else:
        attn_out = _attn_from_norm(h)

    if cfg.parallel_block:
        h2 = h if cfg.shared_parallel_norm else \
            _aq(_norm(x, lp["ln2"], cfg.norm, cfg.norm_eps))
        down, aux = _ffn(h2, lp, cfg)
        return x + attn_out + down, aux

    x = x + attn_out

    def _ffn_delta(xr):
        h2 = _aq(_norm(xr, lp["ln2"], cfg.norm, cfg.norm_eps))
        return _ffn(h2, lp, cfg)

    if cfg.remat == "ffn_block":
        # converse structural remat: bwd recomputes norm2 → FFN (~63% of
        # layer FLOPs); attention residuals (q/k/v/out + flash lse) stay
        # saved. Memory ≈ 6·B·S·H bf16 per layer — the cheaper-storage,
        # smaller-win sibling of attn_block.
        down, aux = jax.checkpoint(_ffn_delta)(x)
    else:
        down, aux = _ffn_delta(x)
    return x + down, aux


def _ffn(h: jax.Array, lp: Dict[str, jax.Array], cfg: TransformerConfig
         ) -> Tuple[jax.Array, jax.Array]:
    """Dense or MoE FFN on normed input; returns (output, aux loss)."""
    dt = cfg.compute_dtype
    aux = jnp.float32(0.0)
    if cfg.n_experts > 0:
        from deepspeed_tpu.moe.layer import moe_ffn

        experts = {k_: lp[k_] for k_ in ("w_up", "w_down", "w_gate") if k_ in lp}
        shared = {k_: lp[k_] for k_ in ("sw_up", "sw_down", "sw_gate",
                                        "shared_gate_w") if k_ in lp}
        down, aux = moe_ffn(
            h, lp["gate_w"], experts, activation=cfg.activation,
            k=cfg.moe_top_k, capacity_factor=cfg.moe_capacity_factor,
            min_capacity=cfg.moe_min_capacity,
            score_func=cfg.moe_score_func, route_norm=cfg.moe_route_norm,
            route_scale=cfg.moe_route_scale, shared=shared or None,
            gate_bias=lp.get("gate_bias"), n_group=cfg.moe_n_group,
            topk_group=cfg.moe_topk_group, dispatch=cfg.moe_dispatch)
    else:
        up = h @ lp["w_up"].astype(dt)
        if cfg.use_bias:
            up = up + lp["b_up"].astype(dt)
        if cfg.activation == "swiglu":
            gate = h @ lp["w_gate"].astype(dt)
            act = jax.nn.silu(gate) * up
        elif cfg.activation == "relu":
            act = jax.nn.relu(up)
        else:
            act = jax.nn.gelu(up, approximate=True)
        act = _ckpt_name(act, "ffn_act")
        down = act @ lp["w_down"].astype(dt)
        if cfg.use_bias:
            down = down + lp["b_down"].astype(dt)
    return down, aux


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #

def forward_hidden(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
                   attention_fn: Optional[AttentionFn] = None,
                   activation_constraint: Optional[Callable[[jax.Array], jax.Array]] = None,
                   pld_keep: Optional[jax.Array] = None,
                   random_ltd_idx: Optional[jax.Array] = None,
                   param_sync: Optional[Callable[[PyTree], PyTree]] = None
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """tokens [B, S] int32 → (final hidden [B, S, H], lm head [H, vocab],
    moe aux loss — summed over layers, 0.0 for dense models).

    ``pld_keep`` [L] float 0/1: progressive-layer-drop mask — a dropped layer
    contributes identity (reference ``runtime/progressive_layer_drop.py``;
    under jit both branches are computed, so PLD acts as the stochastic-depth
    regularizer, not a compute saver — documented TPU semantics).
    ``random_ltd_idx`` [K] sorted positions: random-LTD — the MIDDLE layers
    (all but first and last) run on only these K tokens; dropped tokens skip
    the middle stack via gather/scatter (reference ``data_routing/`` +
    ``csrc/random_ltd``; here the drop set is shared across the middle stack
    so the scan keeps uniform shapes).

    ``cfg.scan_chunks > 1`` splits the layer scan into that many
    sequential chunk scans (``parallel/overlap.py`` even-split) so the
    ZeRO-3 gather of chunk k+1 and the gradient sync of chunk k can
    overlap chunk-adjacent compute; ``param_sync`` (engine-injected,
    ``make_grad_sync``) wraps each chunk's sliced params so its gradient
    sharding constraint is emitted mid-backward. Both are identities —
    the chunked forward is numerically the single-scan forward. The
    random-LTD path keeps its own first/middle/last split and ignores
    chunking (its stacks are already scan-segmented)."""
    attention_fn = attention_fn or dot_product_attention
    constrain = activation_constraint or (lambda x: x)
    dt = cfg.compute_dtype
    B, S = tokens.shape
    L = cfg.num_layers

    x = params["tok_emb"].astype(dt)[tokens]
    if cfg.pos_emb == "learned":
        x = x + params["pos_emb"].astype(dt)[:S][None]
    if cfg.emb_norm:
        x = _norm(x, params["emb_norm"], cfg.norm, cfg.norm_eps)
    x = constrain(x)

    cos = sin = None
    if cfg.pos_emb == "rope":
        rd = cfg.qk_rope_head_dim if cfg.mla else cfg.rope_dim
        cos, sin = rope_table(S, rd, cfg.rope_theta, cfg.rope_scaling_dict)

    def make_body(cos_b, sin_b, with_pld: bool):
        def body(carry, xs):
            if with_pld:
                layer_params, keep = xs
            else:
                layer_params, keep = xs, None
            y, aux = _block_forward(carry, layer_params, cfg, cos_b, sin_b,
                                    attention_fn)
            if keep is not None:
                k = keep.astype(y.dtype)   # don't promote the bf16 carry
                y = k * y + (1 - k) * carry
                aux = keep * aux
            return constrain(y), aux

        return _remat_wrap(body, cfg.remat)

    with_pld = pld_keep is not None

    def run(x, blocks, cos_b, sin_b, keep):
        xs = (blocks, keep) if with_pld else blocks
        return lax.scan(make_body(cos_b, sin_b, with_pld), x, xs)

    def run_chunked(x, blocks, cos_b, sin_b, keep):
        """Sequential per-chunk scans (overlap scheduler granularity).
        Exactly ``run`` when one chunk and no sync hook."""
        from deepspeed_tpu.parallel.overlap import even_chunk_bounds

        bounds = even_chunk_bounds(L, max(cfg.scan_chunks, 1))
        if len(bounds) <= 1 and param_sync is None:
            return run(x, blocks, cos_b, sin_b, keep)
        aux_parts = []
        for start, stop in bounds:
            blk = jax.tree.map(lambda p: p[start:stop], blocks)
            if param_sync is not None:
                blk = param_sync(blk)
            kk = keep[start:stop] if keep is not None else None
            x, aux = run(x, blk, cos_b, sin_b, kk)
            aux_parts.append(aux)
        return x, jnp.concatenate([a.reshape(-1) for a in aux_parts])

    if random_ltd_idx is not None and cfg.pos_emb == "alibi":
        raise NotImplementedError(
            "random-LTD with ALiBi positions is unsupported: the middle-stack "
            "bias would be computed from compacted indices (rope tables are "
            "index-gathered; ALiBi distances cannot be)")
    if random_ltd_idx is None or L < 3:
        x, auxes = run_chunked(x, params["blocks"], cos, sin, pld_keep)
        aux_total = jnp.sum(auxes)
    else:
        blk = params["blocks"]
        first = jax.tree.map(lambda p: p[:1], blk)
        middle = jax.tree.map(lambda p: p[1:L - 1], blk)
        last = jax.tree.map(lambda p: p[L - 1:], blk)
        k1 = k2 = k3 = None
        if with_pld:
            k1, k2, k3 = pld_keep[:1], pld_keep[1:L - 1], pld_keep[L - 1:]
        cos_k = sin_k = None
        if cos is not None:
            cos_k, sin_k = cos[random_ltd_idx], sin[random_ltd_idx]
        x, a1 = run(x, first, cos, sin, k1)
        xk = jnp.take(x, random_ltd_idx, axis=1)          # gather kept
        xk, a2 = run(xk, middle, cos_k, sin_k, k2)
        x = x.at[:, random_ltd_idx].set(xk)               # scatter back
        x, a3 = run(x, last, cos, sin, k3)
        aux_total = jnp.sum(a1) + jnp.sum(a2) + jnp.sum(a3)

    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    head = _lm_head_of(params, cfg)
    return x, head, aux_total


def forward(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
            attention_fn: Optional[AttentionFn] = None,
            activation_constraint: Optional[Callable[[jax.Array], jax.Array]] = None
            ) -> jax.Array:
    """tokens [B, S] int32 → logits [B, S, vocab] in fp32."""
    x, head, _ = forward_hidden(params, tokens, cfg, attention_fn,
                                activation_constraint)
    logits = head_matmul(x, head.astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits


# --------------------------------------------------------------------------- #
# KV-cache decode path (inference)
# --------------------------------------------------------------------------- #

def apply_rope_at(x: jax.Array, cos_table: jax.Array, sin_table: jax.Array,
                  positions: jax.Array) -> jax.Array:
    """Rotate x [B, T, N, D] at absolute ``positions`` [B, T]; partial rotary
    (tables narrower than D/2) passes trailing dims through."""
    rot = 2 * cos_table.shape[-1]
    x_rot, x_pass = x[..., :rot], x[..., rot:]
    d2 = rot // 2
    cos = cos_table[positions][:, :, None, :].astype(x.dtype)  # [B,T,1,rot/2]
    sin = sin_table[positions][:, :, None, :].astype(x.dtype)
    x1, x2 = x_rot[..., :d2], x_rot[..., d2:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if x_pass.shape[-1]:
        out = jnp.concatenate([out, x_pass], axis=-1)
    return out


def init_kv_cache(cfg: TransformerConfig, batch_size: int, max_len: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Layer-stacked KV cache (the blocked-KV analog of the reference's
    ``inference/v2/ragged/kv_cache.py`` — slot-contiguous, length-masked)."""
    dt = dtype or cfg.compute_dtype
    if cfg.mla:
        # MLA caches the LATENT: c_kv [kvr] + shared rope key [dr] per token
        # (the DeepSeek small-cache trick) — stored under the same "k"/"v"
        # keys (head dim 1) so the decode scan plumbing is unchanged
        L, B, M = cfg.num_layers, batch_size, max_len
        return {"k": jnp.zeros((L, B, M, 1, cfg.kv_lora_rank), dt),
                "v": jnp.zeros((L, B, M, 1, cfg.qk_rope_head_dim), dt)}
    shape = (cfg.num_layers, batch_size, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def cached_attention(q: jax.Array, kc: jax.Array, vc: jax.Array,
                     positions: jax.Array,
                     alibi: Optional[jax.Array] = None) -> jax.Array:
    """q [B,T,N,D] at abs ``positions`` [B,T] against cache [B,M,K,D]; causal
    mask = cache index <= query position (fp32 softmax). ``alibi``: [N] slopes;
    bias = slope * (cache_pos - query_pos)."""
    B, T, N, D = q.shape
    M, K = kc.shape[1], kc.shape[2]
    if K != N:
        kc = jnp.repeat(kc, N // K, axis=2)
        vc = jnp.repeat(vc, N // K, axis=2)
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("btnd,bmnd->bntm", q, kc).astype(jnp.float32) * scale
    if alibi is not None:
        rel = (jnp.arange(M)[None, None, :]
               - positions[:, :, None]).astype(jnp.float32)   # [B,T,M]
        scores = scores + alibi[None, :, None, None] * rel[:, None]
    mask = jnp.arange(M)[None, None, None, :] <= positions[:, None, :, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bntm,bmnd->btnd", probs, vc)


def forward_decode(params: PyTree, tokens: jax.Array,
                   cache: Dict[str, jax.Array], pos: jax.Array,
                   cfg: TransformerConfig
                   ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Incremental forward: write new tokens' K/V into the cache and attend.

    tokens [B, T] arriving at positions ``pos[b] .. pos[b]+T-1``; pos [B] int32.
    Works for prefill (T = padded prompt len, pos = 0) and decode (T = 1).
    Returns (logits [B, T, vocab] fp32, updated cache). Parity: the reference's
    inference transformer containers (``module_inject/containers``,
    ``inference/v2/model_implementations``).
    """
    B, T = tokens.shape
    dt = cfg.compute_dtype
    M = cache["k"].shape[2]
    positions = pos[:, None] + jnp.arange(T)[None]          # [B, T]

    x = params["tok_emb"].astype(dt)[tokens]
    if cfg.pos_emb == "learned":
        x = x + params["pos_emb"].astype(dt)[positions]
    if cfg.emb_norm:
        x = _norm(x, params["emb_norm"], cfg.norm, cfg.norm_eps)

    cos_t = sin_t = None
    if cfg.pos_emb == "rope":
        rd = cfg.qk_rope_head_dim if cfg.mla else cfg.rope_dim
        cos_t, sin_t = rope_table(M, rd, cfg.rope_theta, cfg.rope_scaling_dict)
    slopes = (alibi_slopes(cfg.num_heads) * cfg.alibi_bias_scale
              if cfg.pos_emb == "alibi" else None)

    def write(c, new, p):
        return lax.dynamic_update_slice(c, new, (p, 0, 0))

    def body(x, scans):
        from deepspeed_tpu.ops.quantization import dequant_params

        lp, kc, vc = scans
        lp = dequant_params(lp, dt)   # weight-only quant: per-layer dequant
        h = _norm(x, lp["ln1"], cfg.norm, cfg.norm_eps)

        if cfg.mla:
            # kc holds c_kv [B,M,1,kvr]; vc holds the post-rope shared key
            # [B,M,1,dr]. Write the new latents, then: DECODE (T==1) runs
            # WEIGHT-ABSORBED attention directly on the latent cache (W_uk
            # folded into q, W_uv into the output — the per-step k/v
            # re-expansion never happens); PREFILL (T>1) expands once and
            # attends normally — absorbed scores cost O(T·M·N·kvr) which
            # loses to the one-time O(M) expansion for long prompts.
            rope_fn = lambda t: apply_rope_at(t, cos_t, sin_t, positions)
            qf = _mla_q(h, lp, cfg, rope_fn)
            c_kv, k_pe = _mla_latents(h, lp, cfg, rope_fn)
            kc = jax.vmap(write)(kc, c_kv[:, :, None, :].astype(kc.dtype), pos)
            vc = jax.vmap(write)(vc, k_pe.astype(vc.dtype), pos)
            if T == 1:
                attn = _mla_absorbed_attention(
                    qf, kc[:, :, 0].astype(dt), vc[:, :, 0].astype(dt), lp,
                    cfg, positions, cfg.mla_scale_mult)
            else:
                k_full, v_full = _mla_expand(
                    kc[:, :, 0].astype(dt), vc.astype(dt), lp, cfg)
                if cfg.mla_scale_mult != 1.0:
                    qf = qf * jnp.asarray(cfg.mla_scale_mult, qf.dtype)
                attn = cached_attention(qf, k_full, v_full, positions)
            attn = attn.reshape(B, T, cfg.num_heads * cfg.v_head_dim)
            x = x + attn @ lp["wo"].astype(dt)
            h2 = _norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
            down, _ = _ffn(h2, lp, cfg)
            return x + down, (kc, vc)

        def proj(name, shape):
            w = lp[f"w{name}"].astype(dt)
            out = h @ w
            if (cfg.attn_bias_enabled if name in ("q", "k", "v")
                    else cfg.use_bias):
                out = out + lp[f"b{name}"].astype(dt)
            return out.reshape(shape)

        q = proj("q", (B, T, cfg.num_heads, cfg.head_dim))
        k = proj("k", (B, T, cfg.kv_heads, cfg.head_dim))
        v = proj("v", (B, T, cfg.kv_heads, cfg.head_dim))
        if cfg.qk_norm:
            q = _head_rmsnorm(q, lp["q_norm"], cfg.norm_eps)
            k = _head_rmsnorm(k, lp["k_norm"], cfg.norm_eps)
        if cfg.pos_emb == "rope":
            q = apply_rope_at(q, cos_t, sin_t, positions)
            k = apply_rope_at(k, cos_t, sin_t, positions)
        kc = jax.vmap(write)(kc, k.astype(kc.dtype), pos)
        vc = jax.vmap(write)(vc, v.astype(vc.dtype), pos)
        attn = cached_attention(q, kc, vc, positions, alibi=slopes)
        attn = attn.reshape(B, T, cfg.num_heads * cfg.head_dim)
        attn_out = attn @ lp["wo"].astype(dt)
        if cfg.use_bias:
            attn_out = attn_out + lp["bo"].astype(dt)
        if cfg.parallel_block:
            h2 = h if cfg.shared_parallel_norm else \
                _norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
            down, _ = _ffn(h2, lp, cfg)
            return x + attn_out + down, (kc, vc)
        x = x + attn_out
        h2 = _norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
        down, _ = _ffn(h2, lp, cfg)
        return x + down, (kc, vc)

    x, (new_k, new_v) = lax.scan(body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    head = _lm_head_of(params, cfg)
    logits = head_matmul(x, head.astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def _pipeline_parts(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
                    mesh, n_micro, attention_fn, activation_constraint,
                    loss_mask):
    """Shared scaffolding for the GPipe and 1F1B schedules: embedding,
    microbatched inputs, extra params, stage_fn and finalize_fn. Both
    schedules MUST consume this so the 1F1B-vs-GPipe parity tests stay
    meaningful."""
    from deepspeed_tpu.comm.mesh import PIPE_AXIS, get_mesh_manager
    from deepspeed_tpu.parallel.pipeline import microbatch

    if mesh is None:
        mesh = get_mesh_manager().mesh
    n_stages = mesh.shape[PIPE_AXIS]
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pipe={n_stages}")
    if cfg.lm_head_bias:
        raise NotImplementedError(
            "lm_head_bias unsupported in the pipelined path")
    attention_fn = attention_fn or dot_product_attention
    constrain = activation_constraint or (lambda x: x)
    dt = cfg.compute_dtype
    B, S = tokens.shape
    M = n_micro or n_stages

    def embed(embp, toks):
        e = embp["tok_emb"].astype(dt)[toks]
        if cfg.pos_emb == "learned":
            e = e + embp["pos_emb"].astype(dt)[:S][None]
        if cfg.emb_norm:
            e = _norm(e, embp["emb_norm"], cfg.norm, cfg.norm_eps)
        return constrain(e)

    emb_keys = ["tok_emb"]
    if cfg.pos_emb == "learned":
        emb_keys.append("pos_emb")
    if cfg.emb_norm:
        emb_keys.append("emb_norm")
    embp = {k: params[k] for k in emb_keys}

    x = embed(embp, tokens)
    cos = sin = None
    if cfg.pos_emb == "rope":
        rd = cfg.qk_rope_head_dim if cfg.mla else cfg.rope_dim
        cos, sin = rope_table(S, rd, cfg.rope_theta, cfg.rope_scaling_dict)

    head = _lm_head_of(params, cfg)
    inputs = {"x": microbatch(x, M), "tokens": microbatch(tokens, M)}
    if loss_mask is not None:
        inputs["loss_mask"] = microbatch(loss_mask, M)
    extra = {"final_norm": params["final_norm"], "head": head}
    if cos is not None:
        extra["cos"], extra["sin"] = cos, sin

    def stage_fn(x_in, blocks_l, ex):
        def body(carry, lp):
            y, aux = _block_forward(carry, lp, cfg, ex.get("cos"), ex.get("sin"),
                                    attention_fn)
            return constrain(y), aux

        body = _remat_wrap(body, cfg.remat)
        y, auxes = lax.scan(body, x_in, blocks_l)
        return y, jnp.sum(auxes)

    def logits_fn(y, ex):
        """ONE head implementation for every pipeline schedule (training
        loss and forward-only inference must agree). Plain dot (not the
        custom-vjp head_matmul): inside the pipe shard_map the replicated
        head's cotangent needs the automatic varying->replicated psum,
        which a custom_vjp would bypass."""
        h = _norm(y, ex["final_norm"], cfg.norm, cfg.norm_eps)
        return jnp.matmul(h, ex["head"].astype(h.dtype),
                          preferred_element_type=jnp.float32)

    def finalize_fn(y, micro, ex):
        return causal_lm_loss(logits_fn(y, ex), micro["tokens"],
                              micro.get("loss_mask"))

    return mesh, M, embed, embp, inputs, extra, stage_fn, finalize_fn, \
        logits_fn


def pipelined_lm_loss(params: PyTree, tokens: jax.Array, cfg: TransformerConfig,
                      mesh=None, n_micro: Optional[int] = None,
                      attention_fn: Optional[AttentionFn] = None,
                      activation_constraint: Optional[Callable] = None,
                      loss_mask: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """Causal-LM loss with the layer stack pipelined over the 'pipe' mesh axis
    (GPipe forward wavefront — the InferenceSchedule analog; backward via
    autodiff). Returns (loss, moe_aux).
    See ``parallel/pipeline.py`` (reference ``runtime/pipe/engine.py:337``).
    """
    from deepspeed_tpu.parallel.pipeline import pipelined_apply

    mesh, M, _, _, inputs, extra, stage_fn, finalize_fn, _ = _pipeline_parts(
        params, tokens, cfg, mesh, n_micro, attention_fn,
        activation_constraint, loss_mask)
    return pipelined_apply(inputs, params["blocks"], extra, stage_fn,
                           finalize_fn, mesh)


def pipelined_lm_logits(params: PyTree, tokens: jax.Array,
                        cfg: TransformerConfig, mesh=None,
                        n_micro: Optional[int] = None,
                        attention_fn: Optional[AttentionFn] = None,
                        activation_constraint: Optional[Callable] = None
                        ) -> jax.Array:
    """Forward-only pipelined logits (reference ``runtime/pipe/schedule.py:135
    InferenceSchedule``): batched inference across the 'pipe' mesh axis —
    fill wavefront only, no backward machinery. Returns [B, S, vocab] fp32.
    """
    from deepspeed_tpu.parallel.pipeline import pipelined_infer

    mesh, M, _, _, inputs, extra, stage_fn, _, logits_fn = _pipeline_parts(
        params, tokens, cfg, mesh, n_micro, attention_fn,
        activation_constraint, None)

    out = pipelined_infer(inputs, params["blocks"], extra, stage_fn,
                          logits_fn, mesh)                # [M, B/M, S, V]
    B, S = tokens.shape
    return out.reshape(B, S, -1)


def pipelined_lm_loss_and_grads(params: PyTree, tokens: jax.Array,
                                cfg: TransformerConfig, mesh=None,
                                n_micro: Optional[int] = None,
                                attention_fn: Optional[AttentionFn] = None,
                                activation_constraint: Optional[Callable] = None,
                                loss_mask: Optional[jax.Array] = None,
                                loss_scale=None
                                ) -> Tuple[jax.Array, PyTree]:
    """1F1B pipelined loss AND grads (reference ``runtime/pipe/schedule.py:189``
    ``TrainSchedule``): explicit backward schedule with O(P) activation
    residency instead of letting autodiff reverse the GPipe wavefront (O(M)).
    Returns (loss incl. any MoE aux term, grads w.r.t. ``params`` — same
    tree, fp32 leaves). Not supported: ``lm_head_bias`` models (same as the
    GPipe path)."""
    from deepspeed_tpu.parallel.pipeline import pipelined_train_1f1b

    mesh, M, embed, embp, inputs, extra, stage_fn, finalize_fn, _ = \
        _pipeline_parts(params, tokens, cfg, mesh, n_micro, attention_fn,
                        activation_constraint, loss_mask)
    dt = cfg.compute_dtype

    def input_grad_fn(dx, micro, acc):
        if dx is None:   # zeros accumulators (also defines the out structure)
            return jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), embp)
        _, vjp = jax.vjp(lambda ep: embed(ep, micro["tokens"]), embp)
        (d,) = vjp(dx.astype(dt))
        return jax.tree.map(lambda a, g: a + g.astype(jnp.float32), acc, d)

    aux_seed = None
    if cfg.n_experts > 0:
        aux_seed = jnp.float32(cfg.moe_aux_coef) * (
            loss_scale if loss_scale is not None else 1.0)

    loss, aux, gblocks, gextra, gemb = pipelined_train_1f1b(
        inputs, params["blocks"], extra, stage_fn, finalize_fn, input_grad_fn,
        mesh, loss_scale=loss_scale, aux_seed=aux_seed)
    if cfg.n_experts > 0:
        # keep the reported loss comparable with the GPipe path (loss_fn
        # adds the aux term there)
        loss = loss + cfg.moe_aux_coef * aux

    grads: Dict[str, Any] = {"blocks": gblocks,
                             "final_norm": gextra["final_norm"]}
    g_tok = gemb["tok_emb"]
    if cfg.tie_embeddings:
        g_tok = g_tok + gextra["head"].T
    else:
        grads["lm_head"] = gextra["head"]
    grads["tok_emb"] = g_tok
    if cfg.pos_emb == "learned":
        grads["pos_emb"] = gemb["pos_emb"]
    if cfg.emb_norm:
        grads["emb_norm"] = gemb["emb_norm"]
    missing = set(params) - set(grads)
    if missing:
        raise NotImplementedError(
            f"pipelined grads missing for param groups {sorted(missing)}")
    grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
    return loss, grads


def fused_lm_loss(hidden: jax.Array, head: jax.Array, tokens: jax.Array,
                  loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Head projection + next-token CE with a custom VJP tuned for HBM.

    torch-autocast semantics (the reference's fp16/bf16 engines compute
    logits in the low-precision dtype and CE upcasts for the softmax —
    ``torch.nn.CrossEntropyLoss`` under ``autocast``): logits live in the
    COMPUTE dtype (bf16), softmax statistics accumulate in fp32. vs the
    exact-fp32-logits path (``head_matmul`` + ``causal_lm_loss``) this
    halves every [B,S,V] buffer and the custom backward materializes ONE
    bf16 grad-logits array (softmax − onehot fused into its producing
    pass) instead of AD's fp32 grad + scatter-add + convert chain —
    measured ~40 GB → ~18 GB of vocab-axis traffic per micro-batch at
    GPT-2-125M B32 (the loss was ~10%% of step time, PROFILE.md).
    Loss delta vs the exact path is the bf16 logit rounding (~1e-3),
    identical in class to the r2 ``head_matmul`` bf16-cotangent change."""
    B, S, H = hidden.shape
    mask = (jnp.ones((B, S), jnp.float32) if loss_mask is None
            else loss_mask.astype(jnp.float32))

    @jax.custom_vjp
    def _loss(x, w):
        return _fwd(x, w)[0]

    def _fwd(x, w):
        dt = x.dtype
        wc = w.astype(dt)
        xs = x[:, :-1]
        tgt = tokens[:, 1:]
        # one bf16 [B,S-1,V] buffer; the f32-accumulated matmul casts in
        # its epilogue, logsumexp upconverts in its reduce
        logits = jnp.matmul(xs, wc,
                            preferred_element_type=jnp.float32).astype(dt)
        lf = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(lf, axis=-1)
        picked = jnp.take_along_axis(lf, tgt[..., None], axis=-1)[..., 0]
        m = mask[:, 1:]
        cnt = jnp.maximum(jnp.sum(m), 1.0)
        loss = jnp.sum((logz - picked) * m) / cnt
        return loss, (logits, logz, xs, wc, tgt, m, cnt)

    def _bwd(res, g):
        logits, logz, xs, wc, tgt, m, cnt = res
        dt = xs.dtype
        coef = (m * (g / cnt))[..., None]
        one = (lax.broadcasted_iota(jnp.int32, logits.shape, 2)
               == tgt[..., None])
        # single fused pass: read bf16 logits, exp, subtract onehot, scale,
        # write bf16 grad-logits — feeds both backward matmuls
        gl = ((jnp.exp(logits.astype(jnp.float32) - logz[..., None])
               - one.astype(jnp.float32)) * coef).astype(dt)
        dx = jnp.matmul(gl, wc.T, preferred_element_type=jnp.float32) \
            .astype(dt)
        dw = jnp.matmul(xs.reshape(-1, xs.shape[-1]).T,
                        gl.reshape(-1, gl.shape[-1]),
                        preferred_element_type=jnp.float32)
        dx = jnp.pad(dx, ((0, 0), (0, 1), (0, 0)))
        return dx, dw.astype(head.dtype)

    _loss.defvjp(_fwd, _bwd)
    return _loss(hidden, head)


def causal_lm_loss(logits: jax.Array, tokens: jax.Array,
                   loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross entropy; stable log-softmax in fp32."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    # logsumexp - picked (not log_softmax + gather): avoids materializing a
    # second [B, S, V] log-prob buffer — HBM bandwidth is the constraint here.
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - picked
    if loss_mask is not None:
        mask = loss_mask[:, 1:].astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


# --------------------------------------------------------------------------- #
# presets (names mirror the driver's milestone configs, BASELINE.md)
# --------------------------------------------------------------------------- #

PRESETS: Dict[str, TransformerConfig] = {
    "tiny": TransformerConfig(vocab_size=512, hidden_size=64, num_layers=2,
                              num_heads=4, max_seq_len=128),
    "tiny_llama": TransformerConfig(vocab_size=512, hidden_size=64, num_layers=2,
                                    num_heads=4, num_kv_heads=2, max_seq_len=128,
                                    pos_emb="rope", norm="rmsnorm",
                                    activation="swiglu", use_bias=False,
                                    tie_embeddings=False),
    "gpt2_125m": TransformerConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                                   num_heads=12, max_seq_len=1024),
    "gpt2_350m": TransformerConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                                   num_heads=16, max_seq_len=1024),
    "gpt2_1p5b": TransformerConfig(vocab_size=50304, hidden_size=1600, num_layers=48,
                                   num_heads=25, max_seq_len=1024),
    "bert_large": TransformerConfig(vocab_size=30528, hidden_size=1024, num_layers=24,
                                    num_heads=16, max_seq_len=512, causal=False),
    # llama-style model sized so fp32 master + Adam moments + fp32 grads fit a
    # single 16G-HBM chip (ZeRO-3 single-host bench; ~665M params ≈ 12G state)
    "llama_750m": TransformerConfig(vocab_size=32000, hidden_size=1536,
                                    num_layers=20, num_heads=12,
                                    ffn_hidden_size=4096, max_seq_len=2048,
                                    pos_emb="rope", norm="rmsnorm",
                                    activation="swiglu", use_bias=False,
                                    tie_embeddings=False),
    # mixtral-style MoE sized for one chip (4 experts, top-2)
    "moe_350m": TransformerConfig(vocab_size=32000, hidden_size=768,
                                  num_layers=12, num_heads=12, max_seq_len=1024,
                                  use_bias=False, n_experts=4, moe_top_k=2),
    # larger-expert MoE (~2B total / ~0.7B active): hidden 1536 (head_dim
    # 128) and expert-ffn 6144 put the grouped GEMM at shapes where it
    # matches dense matmul throughput (46-55 TF/s grouped vs 52 dense at
    # [32k,1536]x[8,1536,6144], same-harness A/B) — at moe_350m's K=768
    # shapes grouped and dense measure in the SAME low band, i.e. the
    # contraction itself is the ceiling; full rung table in PROFILE.md r5
    "moe_1b": TransformerConfig(vocab_size=32000, hidden_size=1536,
                                num_layers=12, num_heads=12, max_seq_len=1024,
                                ffn_hidden_size=6144, use_bias=False,
                                n_experts=8, moe_top_k=2),
    # north-star-scale single-chip model (BASELINE.md): ~3.1B params with
    # MXU-aligned shapes — head_dim 128, ffn 8192 (the open-llama-3B layout's
    # head_dim 100 wastes MXU lanes; this keeps every contraction 128-tiled)
    "llama_3b": TransformerConfig(vocab_size=32000, hidden_size=3072,
                                  num_layers=26, num_heads=24,
                                  ffn_hidden_size=8192, max_seq_len=2048,
                                  pos_emb="rope", norm="rmsnorm",
                                  activation="swiglu", use_bias=False,
                                  tie_embeddings=False),
    "llama2_7b": TransformerConfig(vocab_size=32000, hidden_size=4096, num_layers=32,
                                   num_heads=32, ffn_hidden_size=11008,
                                   max_seq_len=4096, pos_emb="rope", norm="rmsnorm",
                                   activation="swiglu", use_bias=False,
                                   tie_embeddings=False),
    "llama2_13b": TransformerConfig(vocab_size=32000, hidden_size=5120, num_layers=40,
                                    num_heads=40, ffn_hidden_size=13824,
                                    max_seq_len=4096, pos_emb="rope", norm="rmsnorm",
                                    activation="swiglu", use_bias=False,
                                    tie_embeddings=False),
    "tiny_moe": TransformerConfig(vocab_size=512, hidden_size=64, num_layers=2,
                                  num_heads=4, max_seq_len=128, use_bias=False,
                                  n_experts=4, moe_top_k=2),
    "mixtral_8x7b": TransformerConfig(vocab_size=32000, hidden_size=4096,
                                      num_layers=32, num_heads=32, num_kv_heads=8,
                                      ffn_hidden_size=14336, max_seq_len=4096,
                                      pos_emb="rope", norm="rmsnorm",
                                      activation="swiglu", use_bias=False,
                                      tie_embeddings=False,
                                      n_experts=8, moe_top_k=2),
}


def get_model_config(name: str, **overrides) -> TransformerConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown model preset {name!r}; available: {sorted(PRESETS)}")
    return dataclasses.replace(PRESETS[name], **overrides)
