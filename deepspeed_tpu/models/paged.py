"""Paged (block-table) KV forward pass — the FastGen blocked-KV analog.

Parity: reference ``inference/v2/ragged/kv_cache.py:1-208`` (blocked KV with a
host-side allocator) + ``inference/v2/kernels/ragged_ops`` (blocked attention /
KV writes that take a ragged batch of mixed prefill chunks and decode tokens).

TPU design: XLA wants one static shape, so the ragged batch is a FLAT token
batch of fixed budget T: each tick packs decode tokens (one per running
sequence) and prefill chunks (Dynamic SplitFuse) into ``tokens[T]`` with
per-token ``positions[T]`` and ``tables[T, MB]`` (the owning sequence's block
table). The KV pool is ``[L, NB, bs, K, D]``; token (t) writes its K/V at
``pool[tables[t, pos//bs], pos % bs]`` and attends to its first ``pos+1``
cache slots via block gathers. Pad tokens carry an all-zeros table and write
into reserved trash block 0.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models import transformer as T

PyTree = Any


def init_paged_kv(cfg: T.TransformerConfig, n_blocks: int, block_size: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Block pool per layer. Block 0 is the trash block for pad writes."""
    dt = dtype or cfg.compute_dtype
    shape = (cfg.num_layers, n_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_attention_reference(q: jax.Array, kpool: jax.Array, vpool: jax.Array,
                              tables: jax.Array, lengths: jax.Array
                              ) -> jax.Array:
    """Pure-XLA paged attention (the CPU/fallback path; the Pallas kernel in
    ``ops/pallas/paged_attention.py`` computes the same thing without
    materializing the gathered KV).

    q [T, N, D]; pools [NB, bs, K, D]; tables [T, MB]; lengths [T] (= pos+1).
    Token t attends to its sequence's first ``lengths[t]`` cache slots.
    """
    Tn, N, D = q.shape
    bs = kpool.shape[1]
    K = kpool.shape[2]
    MB = tables.shape[1]
    kg = kpool[tables]                                   # [T, MB, bs, K, D]
    vg = vpool[tables]
    kg = kg.reshape(Tn, MB * bs, K, D)
    vg = vg.reshape(Tn, MB * bs, K, D)
    if K != N:
        kg = jnp.repeat(kg, N // K, axis=2)
        vg = jnp.repeat(vg, N // K, axis=2)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    s = jnp.einsum("tnd,tcnd->tnc", q.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale       # [T, N, ctx]
    mask = jnp.arange(MB * bs)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("tnc,tcnd->tnd", p, vg.astype(jnp.float32)).astype(q.dtype)


def forward_paged(params: PyTree, tokens: jax.Array, positions: jax.Array,
                  tables: jax.Array, pool: Dict[str, jax.Array],
                  cfg: T.TransformerConfig,
                  attention_fn: Optional[Callable] = None
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One SplitFuse tick over a flat token batch.

    MLA (DeepSeek) models are not supported here yet — the paged pool is
    laid out per (kv_head, head_dim); serve those through the v1
    InferenceEngine (its latent-cache decode path handles MLA).

    tokens [T] int32, positions [T] int32, tables [T, MB] int32 (rows shared
    by tokens of the same sequence). Returns (logits [T, vocab] fp32,
    updated pool). Parity: the reference's model-implementation forward over
    a RaggedBatchWrapper (``inference/v2/model_implementations``).
    """
    if cfg.mla:
        raise NotImplementedError(
            "MLA (DeepSeek) models are not supported by the paged/FastGen "
            "path yet; use the v1 InferenceEngine (latent-cache decode)")
    attention_fn = attention_fn or paged_attention_reference
    dt = cfg.compute_dtype
    Tn = tokens.shape[0]
    bs = pool["k"].shape[2]

    x = params["tok_emb"].astype(dt)[tokens]             # [T, H]
    if cfg.pos_emb == "learned":
        x = x + params["pos_emb"].astype(dt)[positions]
    if cfg.emb_norm:
        x = T._norm(x, params["emb_norm"], cfg.norm, cfg.norm_eps)

    max_pos = pool["k"].shape[1] * bs
    cos_t = sin_t = None
    if cfg.pos_emb == "rope":
        cos_t, sin_t = T.rope_table(max_pos, cfg.rope_dim, cfg.rope_theta,
                                    cfg.rope_scaling_dict)
    block_idx = jnp.take_along_axis(
        tables, (positions // bs)[:, None], axis=1)[:, 0]  # [T]
    offsets = positions % bs
    lengths = positions + 1

    # The pool rides the layer scan as a FLAT [L*NB, bs, K, D] carry that is
    # scattered in place (layer l owns block range [l*NB, (l+1)*NB)); the
    # attention kernel gathers through layer-offset tables, reading only the
    # listed blocks. Threading per-layer slices as scan xs→ys (the naive
    # layout) re-stacks the ENTIRE pool every call — measured 25 ms/tick at
    # 512 blocks inside a decode scan, linear in pool size — where the
    # in-place carry touches only the written rows.
    L, NB = pool["k"].shape[0], pool["k"].shape[1]
    flat = (L * NB,) + pool["k"].shape[2:]

    def body(carry, lp):
        from deepspeed_tpu.ops.quantization import dequant_params

        x, pk, pv, li = carry
        lp = dequant_params(lp, dt)   # weight-only quant: per-layer dequant
        h = T._norm(x, lp["ln1"], cfg.norm, cfg.norm_eps)

        def proj(name, shape):
            w = lp[f"w{name}"].astype(dt)
            out = h @ w
            if (cfg.attn_bias_enabled if name in ("q", "k", "v")
                    else cfg.use_bias):
                out = out + lp[f"b{name}"].astype(dt)
            return out.reshape(shape)

        q = proj("q", (Tn, cfg.num_heads, cfg.head_dim))
        k = proj("k", (Tn, cfg.kv_heads, cfg.head_dim))
        v = proj("v", (Tn, cfg.kv_heads, cfg.head_dim))
        if cfg.qk_norm:
            q = T._head_rmsnorm(q, lp["q_norm"], cfg.norm_eps)
            k = T._head_rmsnorm(k, lp["k_norm"], cfg.norm_eps)
        if cfg.pos_emb == "rope":
            q = T.apply_rope_at(q[None], cos_t, sin_t, positions[None])[0]
            k = T.apply_rope_at(k[None], cos_t, sin_t, positions[None])[0]
        # blocked KV write (reference ragged_ops KV-copy kernels): token t →
        # pool[l*NB + block_idx[t], offsets[t]]. Pad tokens hit this layer's
        # trash block (block 0 of its range — never allocated).
        base = li * NB
        pk = pk.at[base + block_idx, offsets].set(k.astype(pk.dtype),
                                                  mode="drop")
        pv = pv.at[base + block_idx, offsets].set(v.astype(pv.dtype),
                                                  mode="drop")

        attn = attention_fn(q, pk, pv, tables + base, lengths)  # [T, N, D]
        attn = attn.reshape(Tn, cfg.num_heads * cfg.head_dim)
        attn_out = attn @ lp["wo"].astype(dt)
        if cfg.use_bias:
            attn_out = attn_out + lp["bo"].astype(dt)
        if cfg.parallel_block:
            h2 = h if cfg.shared_parallel_norm else \
                T._norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
            down, _ = T._ffn(h2, lp, cfg)
            return (x + attn_out + down, pk, pv, li + 1), None
        x = x + attn_out
        h2 = T._norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
        down, _ = T._ffn(h2, lp, cfg)
        return (x + down, pk, pv, li + 1), None

    carry0 = (x, pool["k"].reshape(flat), pool["v"].reshape(flat),
              jnp.int32(0))
    (x, new_k, new_v, _), _ = lax.scan(body, carry0, params["blocks"])
    new_k = new_k.reshape(pool["k"].shape)
    new_v = new_v.reshape(pool["v"].shape)
    x = T._norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    head = T._lm_head_of(params, cfg)
    logits = T.head_matmul(x, head.astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}
