"""Paged (block-table) KV forward pass — the FastGen blocked-KV analog.

Parity: reference ``inference/v2/ragged/kv_cache.py:1-208`` (blocked KV with a
host-side allocator) + ``inference/v2/kernels/ragged_ops`` (blocked attention /
KV writes that take a ragged batch of mixed prefill chunks and decode tokens).

TPU design: XLA wants one static shape, so the ragged batch is a FLAT token
batch of fixed budget T: each tick packs decode tokens (one per running
sequence) and prefill chunks (Dynamic SplitFuse) into ``tokens[T]`` with
per-token ``positions[T]`` and ``tables[T, MB]`` (the owning sequence's block
table). The KV pool is ``[L, NB, bs, K, D]``; token (t) writes its K/V at
``pool[tables[t, pos//bs], pos % bs]`` and attends to its first ``pos+1``
cache slots via block gathers. Pad tokens carry an all-zeros table and write
into reserved trash block 0.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models import transformer as T

PyTree = Any


def init_paged_kv(cfg: T.TransformerConfig, n_blocks: int, block_size: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Block pool per layer. Block 0 is the trash block for pad writes.

    MLA models (DeepSeek) pool the LATENTS instead of per-head K/V —
    c_kv [.., kv_lora_rank] + shared post-rope key [.., qk_rope_head_dim]
    per slot (reference ``ragged/kv_cache.py`` + the v2 engine's DeepSeek
    containers). That tiny row width (kvr+dr vs 2·K·D) is exactly where
    paged KV pays off."""
    dt = dtype or cfg.compute_dtype
    L = cfg.num_layers
    if cfg.mla:
        return {"ckv": jnp.zeros((L, n_blocks, block_size,
                                  cfg.kv_lora_rank), dt),
                "kpe": jnp.zeros((L, n_blocks, block_size,
                                  cfg.qk_rope_head_dim), dt)}
    shape = (L, n_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def paged_attention_reference(q: jax.Array, kpool: jax.Array, vpool: jax.Array,
                              tables: jax.Array, lengths: jax.Array,
                              alibi: Optional[jax.Array] = None
                              ) -> jax.Array:
    """Pure-XLA paged attention (the CPU/fallback path; the Pallas kernel in
    ``ops/pallas/paged_attention.py`` computes the same thing without
    materializing the gathered KV).

    q [T, N, D]; pools [NB, bs, K, D]; tables [T, MB]; lengths [T] (= pos+1).
    Token t attends to its sequence's first ``lengths[t]`` cache slots.
    ``alibi``: [N] slopes — cache slot c IS absolute position c, so the
    bias is ``slope · (c − (lengths−1))`` (matches ``cached_attention``).
    """
    Tn, N, D = q.shape
    bs = kpool.shape[1]
    K = kpool.shape[2]
    MB = tables.shape[1]
    kg = kpool[tables]                                   # [T, MB, bs, K, D]
    vg = vpool[tables]
    kg = kg.reshape(Tn, MB * bs, K, D)
    vg = vg.reshape(Tn, MB * bs, K, D)
    if K != N:
        kg = jnp.repeat(kg, N // K, axis=2)
        vg = jnp.repeat(vg, N // K, axis=2)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    s = jnp.einsum("tnd,tcnd->tnc", q.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale       # [T, N, ctx]
    if alibi is not None:
        rel = (jnp.arange(MB * bs)[None, :]
               - (lengths[:, None] - 1)).astype(jnp.float32)  # [T, ctx]
        s = s + alibi.astype(jnp.float32)[None, :, None] * rel[:, None, :]
    mask = jnp.arange(MB * bs)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("tnc,tcnd->tnd", p, vg.astype(jnp.float32)).astype(q.dtype)


def grouped_prefill_attention(q: jax.Array, kpool: jax.Array,
                              vpool: jax.Array, group_tables: jax.Array,
                              lengths: jax.Array,
                              alibi: Optional[jax.Array] = None) -> jax.Array:
    """Attention for CHUNK-ALIGNED prefill rows: one block gather per GROUP.

    The planned SplitFuse schedule packs prefill rows so that each
    consecutive group of C rows belongs to ONE sequence (pad rows allowed);
    all rows of a group therefore share a block table and the group gathers
    its KV blocks ONCE — C× less pool traffic and C× fewer table walks than
    the per-token paths, which is what makes prefill ticks run at compute
    speed instead of gather speed (measured 37 ms → ~3 ms per 512-row tick
    on a v5e). q [R, N, D] with R = G·C; group_tables [G, MB];
    lengths [R] (pos+1; pad rows have length ≤ 1 and head=False upstream).
    Cache slot c of a group's gathered context IS absolute position c, so
    causality is just ``c < length(row)`` — same mask rule as the per-token
    reference.
    """
    R, N, D = q.shape
    G, MB = group_tables.shape
    C = R // G
    bs = kpool.shape[1]
    K = kpool.shape[2]
    S = MB * bs
    kg = kpool[group_tables].reshape(G, S, K, D)         # [G, S, K, D]
    vg = vpool[group_tables].reshape(G, S, K, D)
    if K != N:
        kg = jnp.repeat(kg, N // K, axis=2)
        vg = jnp.repeat(vg, N // K, axis=2)
    qg = q.reshape(G, C, N, D)
    lg = lengths.reshape(G, C)
    scale = 1.0 / jnp.sqrt(jnp.float32(D))
    s = jnp.einsum("gcnd,gsnd->gcns", qg.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale       # [G, C, N, S]
    if alibi is not None:
        rel = (jnp.arange(S)[None, None, :]
               - (lg[:, :, None] - 1)).astype(jnp.float32)     # [G, C, S]
        s = s + alibi.astype(jnp.float32)[None, None, :, None] \
            * rel[:, :, None, :]
    mask = jnp.arange(S)[None, None, None, :] < lg[:, :, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("gcns,gsnd->gcnd", p, vg.astype(jnp.float32))
    return out.reshape(R, N, D).astype(q.dtype)


def paged_mla_attention_reference(q: jax.Array, ckv_pool: jax.Array,
                                  kpe_pool: jax.Array, tables: jax.Array,
                                  lengths: jax.Array, w_kv_b: jax.Array,
                                  cfg: T.TransformerConfig) -> jax.Array:
    """Weight-absorbed MLA attention over the paged LATENT pool (the
    DeepSeek decode trick of ``transformer._mla_absorbed_attention``, paged):
    W_uk folds into the query and W_uv into the output, so each cache slot
    is read ONCE at width kvr+dr and k/v are never re-expanded.

    q [T, N, dn+dr] (post-rope); ckv_pool [NBf, bs, kvr];
    kpe_pool [NBf, bs, dr]; tables [T, MB]; → [T, N, dv].
    """
    import math

    dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
    kvr, N = cfg.kv_lora_rank, cfg.num_heads
    Tn = q.shape[0]
    bs = ckv_pool.shape[1]
    MB = tables.shape[1]
    dt = q.dtype
    ckv = ckv_pool[tables].reshape(Tn, MB * bs, kvr)
    kpe = kpe_pool[tables].reshape(Tn, MB * bs, dr)
    w_kv = w_kv_b.astype(dt).reshape(kvr, N, dn + dv)
    w_uk, w_uv = w_kv[..., :dn], w_kv[..., dn:]
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    q_lat = jnp.einsum("tnd,knd->tnk", q_nope, w_uk)     # [T, N, kvr]
    scale = cfg.mla_scale_mult / math.sqrt(dn + dr)
    s = (jnp.einsum("tnk,tck->tnc", q_lat, ckv)
         + jnp.einsum("tnr,tcr->tnc", q_pe, kpe)).astype(jnp.float32) * scale
    mask = jnp.arange(MB * bs)[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    out_lat = jnp.einsum("tnc,tck->tnk", p, ckv)         # [T, N, kvr]
    return jnp.einsum("tnk,knd->tnd", out_lat, w_uv)     # [T, N, dv]


def forward_paged(params: PyTree, tokens: jax.Array, positions: jax.Array,
                  tables: jax.Array, pool: Dict[str, jax.Array],
                  cfg: T.TransformerConfig,
                  attention_fn: Optional[Callable] = None,
                  group_tables: Optional[jax.Array] = None,
                  n_decode: int = 0
                  ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One SplitFuse tick over a flat token batch.

    tokens [T] int32, positions [T] int32, tables [T, MB] int32 (rows shared
    by tokens of the same sequence). Returns (logits [T, vocab] fp32,
    updated pool). Parity: the reference's model-implementation forward over
    a RaggedBatchWrapper (``inference/v2/model_implementations``).

    ``group_tables`` [G, MB] (planned ticks): rows [n_decode:] are
    chunk-aligned — group g of C = (T - n_decode)/G consecutive rows
    belongs to one sequence with table ``group_tables[g]`` and attends via
    :func:`grouped_prefill_attention` (one gather per group); only the
    first ``n_decode`` rows (per-row tables) walk the per-token path. The
    KV WRITE path always uses the per-row tables.

    MLA (DeepSeek) models pool latents and attend weight-absorbed
    (:func:`paged_mla_attention_reference`); ALiBi models (BLOOM/Falcon)
    bias the paged scores by head slope × relative position.
    """
    if cfg.mla:
        return _forward_paged_mla(params, tokens, positions, tables, pool,
                                  cfg)
    attention_fn = attention_fn or paged_attention_reference
    alibi = None
    if cfg.pos_emb == "alibi":
        # the Pallas kernel has no bias input yet — ALiBi ticks use the
        # XLA reference path (correct, rectangular-gather cost)
        attention_fn = paged_attention_reference
        alibi = T.alibi_slopes(cfg.num_heads) * cfg.alibi_bias_scale
    dt = cfg.compute_dtype
    Tn = tokens.shape[0]
    bs = pool["k"].shape[2]

    x = params["tok_emb"].astype(dt)[tokens]             # [T, H]
    if cfg.pos_emb == "learned":
        x = x + params["pos_emb"].astype(dt)[positions]
    if cfg.emb_norm:
        x = T._norm(x, params["emb_norm"], cfg.norm, cfg.norm_eps)

    max_pos = pool["k"].shape[1] * bs
    cos_t = sin_t = None
    if cfg.pos_emb == "rope":
        cos_t, sin_t = T.rope_table(max_pos, cfg.rope_dim, cfg.rope_theta,
                                    cfg.rope_scaling_dict)
    block_idx = jnp.take_along_axis(
        tables, (positions // bs)[:, None], axis=1)[:, 0]  # [T]
    offsets = positions % bs
    lengths = positions + 1

    # The pool rides the layer scan as a FLAT [L*NB, bs, K, D] carry that is
    # scattered in place (layer l owns block range [l*NB, (l+1)*NB)); the
    # attention kernel gathers through layer-offset tables, reading only the
    # listed blocks. Threading per-layer slices as scan xs→ys (the naive
    # layout) re-stacks the ENTIRE pool every call — measured 25 ms/tick at
    # 512 blocks inside a decode scan, linear in pool size — where the
    # in-place carry touches only the written rows.
    L, NB = pool["k"].shape[0], pool["k"].shape[1]
    flat = (L * NB,) + pool["k"].shape[2:]

    def body(carry, lp):
        from deepspeed_tpu.ops.quantization import dequant_params

        x, pk, pv, li = carry
        lp = dequant_params(lp, dt)   # weight-only quant: per-layer dequant
        h = T._norm(x, lp["ln1"], cfg.norm, cfg.norm_eps)

        def proj(name, shape):
            w = lp[f"w{name}"].astype(dt)
            out = h @ w
            if (cfg.attn_bias_enabled if name in ("q", "k", "v")
                    else cfg.use_bias):
                out = out + lp[f"b{name}"].astype(dt)
            return out.reshape(shape)

        q = proj("q", (Tn, cfg.num_heads, cfg.head_dim))
        k = proj("k", (Tn, cfg.kv_heads, cfg.head_dim))
        v = proj("v", (Tn, cfg.kv_heads, cfg.head_dim))
        if cfg.qk_norm:
            q = T._head_rmsnorm(q, lp["q_norm"], cfg.norm_eps)
            k = T._head_rmsnorm(k, lp["k_norm"], cfg.norm_eps)
        if cfg.pos_emb == "rope":
            q = T.apply_rope_at(q[None], cos_t, sin_t, positions[None])[0]
            k = T.apply_rope_at(k[None], cos_t, sin_t, positions[None])[0]
        # blocked KV write (reference ragged_ops KV-copy kernels): token t →
        # pool[l*NB + block_idx[t], offsets[t]]. Pad tokens hit this layer's
        # trash block (block 0 of its range — never allocated).
        base = li * NB
        pk = pk.at[base + block_idx, offsets].set(k.astype(pk.dtype),
                                                  mode="drop")
        pv = pv.at[base + block_idx, offsets].set(v.astype(pv.dtype),
                                                  mode="drop")

        if group_tables is not None:
            parts = []
            if n_decode:
                parts.append(
                    attention_fn(q[:n_decode], pk, pv,
                                 tables[:n_decode] + base,
                                 lengths[:n_decode],
                                 **({"alibi": alibi} if alibi is not None
                                    else {})))
            parts.append(grouped_prefill_attention(
                q[n_decode:], pk, pv, group_tables + base,
                lengths[n_decode:], alibi=alibi))
            attn = jnp.concatenate(parts, axis=0) if n_decode else parts[0]
        elif alibi is not None:
            attn = attention_fn(q, pk, pv, tables + base, lengths,
                                alibi=alibi)                    # [T, N, D]
        else:
            attn = attention_fn(q, pk, pv, tables + base, lengths)
        attn = attn.reshape(Tn, cfg.num_heads * cfg.head_dim)
        attn_out = attn @ lp["wo"].astype(dt)
        if cfg.use_bias:
            attn_out = attn_out + lp["bo"].astype(dt)
        if cfg.parallel_block:
            h2 = h if cfg.shared_parallel_norm else \
                T._norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
            down, _ = T._ffn(h2, lp, cfg)
            return (x + attn_out + down, pk, pv, li + 1), None
        x = x + attn_out
        h2 = T._norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
        down, _ = T._ffn(h2, lp, cfg)
        return (x + down, pk, pv, li + 1), None

    carry0 = (x, pool["k"].reshape(flat), pool["v"].reshape(flat),
              jnp.int32(0))
    (x, new_k, new_v, _), _ = lax.scan(body, carry0, params["blocks"])
    new_k = new_k.reshape(pool["k"].shape)
    new_v = new_v.reshape(pool["v"].shape)
    x = T._norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    head = T._lm_head_of(params, cfg)
    logits = T.head_matmul(x, head.astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def _forward_paged_mla(params: PyTree, tokens: jax.Array,
                       positions: jax.Array, tables: jax.Array,
                       pool: Dict[str, jax.Array], cfg: T.TransformerConfig
                       ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """MLA SplitFuse tick: write c_kv/k_pe LATENTS into the paged pool and
    attend weight-absorbed (same flat in-place pool carry as the dense
    path; same math as the v1 engine's latent-cache decode)."""
    dt = cfg.compute_dtype
    Tn = tokens.shape[0]
    bs = pool["ckv"].shape[2]

    x = params["tok_emb"].astype(dt)[tokens]
    if cfg.emb_norm:
        x = T._norm(x, params["emb_norm"], cfg.norm, cfg.norm_eps)

    max_pos = pool["ckv"].shape[1] * bs
    cos_t, sin_t = T.rope_table(max_pos, cfg.qk_rope_head_dim,
                                cfg.rope_theta, cfg.rope_scaling_dict)

    def rope_fn(v):                                   # v [T, 1, n, dr]
        return T.apply_rope_at(v, cos_t, sin_t, positions[:, None])

    block_idx = jnp.take_along_axis(
        tables, (positions // bs)[:, None], axis=1)[:, 0]
    offsets = positions % bs
    lengths = positions + 1
    L, NB = pool["ckv"].shape[0], pool["ckv"].shape[1]
    fck = (L * NB,) + pool["ckv"].shape[2:]
    fkp = (L * NB,) + pool["kpe"].shape[2:]

    def body(carry, lp):
        from deepspeed_tpu.ops.quantization import dequant_params

        x, pck, pkp, li = carry
        lp = dequant_params(lp, dt)
        h = T._norm(x, lp["ln1"], cfg.norm, cfg.norm_eps)
        hB = h[:, None, :]                            # [T, 1, H]
        q = T._mla_q(hB, lp, cfg, rope_fn)[:, 0]      # [T, N, dn+dr]
        c_kv, k_pe = T._mla_latents(hB, lp, cfg, rope_fn)
        ckv_t = c_kv[:, 0]                            # [T, kvr]
        kpe_t = k_pe[:, 0, 0]                         # [T, dr]

        base = li * NB
        pck = pck.at[base + block_idx, offsets].set(
            ckv_t.astype(pck.dtype), mode="drop")
        pkp = pkp.at[base + block_idx, offsets].set(
            kpe_t.astype(pkp.dtype), mode="drop")

        attn = paged_mla_attention_reference(
            q, pck, pkp, tables + base, lengths, lp["wkv_b"], cfg)
        attn = attn.reshape(Tn, cfg.num_heads * cfg.v_head_dim)
        attn_out = attn @ lp["wo"].astype(dt)
        x = x + attn_out
        h2 = T._norm(x, lp["ln2"], cfg.norm, cfg.norm_eps)
        down, _ = T._ffn(h2, lp, cfg)
        return (x + down, pck, pkp, li + 1), None

    carry0 = (x, pool["ckv"].reshape(fck), pool["kpe"].reshape(fkp),
              jnp.int32(0))
    (x, new_ck, new_kp, _), _ = lax.scan(body, carry0, params["blocks"])
    x = T._norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    head = T._lm_head_of(params, cfg)
    logits = T.head_matmul(x, head.astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits, {"ckv": new_ck.reshape(pool["ckv"].shape),
                    "kpe": new_kp.reshape(pool["kpe"].shape)}
