#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Metric: tokens/sec/chip for GPT-2-125M causal-LM training (ZeRO-1, bf16,
fused jitted train step) on the available device(s). ``vs_baseline`` compares
against an estimated NCCL/A100 DeepSpeed throughput for the same model
(A100 bf16 peak 312 TFLOPs at ~40% MFU → ~167k tokens/s for a 125M-param model;
see BASELINE.md — the reference publishes no directly comparable table).
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "")


def main():
    import jax
    import numpy as np

    import deepspeed_tpu as dst
    from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

    n_chips = jax.device_count()
    batch_per_chip = int(os.environ.get("BENCH_BATCH", 8))
    seq_len = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 8))
    gas = int(os.environ.get("BENCH_GAS", 8))
    model = os.environ.get("BENCH_MODEL", "gpt2_125m")

    # flash attention (no [S,S] score materialization — fits 16G HBM at
    # batch 8 x 1024) + per-layer remat; gas micro-batches scanned INSIDE one
    # jitted step so per-dispatch overhead amortizes over gas x batch x seq
    # tokens.
    attention = os.environ.get("BENCH_ATTENTION",
                               "flash" if model != "tiny" else "xla")
    spec = dst.causal_lm_spec(model, remat="dots_saveable",
                              attention=attention)
    config = {
        "train_batch_size": batch_per_chip * gas * n_chips,
        "train_micro_batch_size_per_gpu": batch_per_chip,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }
    engine, *_ = dst.initialize(model=spec, config=config)
    data = synthetic_lm_data(batch_per_chip * n_chips, seq_len,
                             spec_vocab(spec), seed=0)

    # warmup (compile); float() forces a real host sync (block_until_ready
    # may return early through remote-execution tunnels)
    for _ in range(2):
        loss = engine.train_batch(data)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(data)
    float(loss)
    dt = time.perf_counter() - t0

    tokens = steps * gas * batch_per_chip * n_chips * seq_len
    tokens_per_sec_chip = tokens / dt / n_chips
    baseline = 167_000.0  # est. A100 DeepSpeed tokens/s/GPU for 125M @ 40% MFU
    print(json.dumps({
        "metric": f"tokens/sec/chip {model} zero1 bf16",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec_chip / baseline, 3),
    }))


def spec_vocab(spec):
    from deepspeed_tpu.models.transformer import PRESETS

    return PRESETS[os.environ.get("BENCH_MODEL", "gpt2_125m")].vocab_size


if __name__ == "__main__":
    sys.exit(main())
