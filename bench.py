#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Headline metric: tokens/sec/chip for GPT-2-125M causal-LM training (ZeRO-1,
bf16, fused jitted train step). ``vs_baseline`` compares achieved model
TFLOP/s against the reference's own best PUBLISHED sustained rate — 175
TFLOP/s/GPU (>54% of A100 peak, DeepSpeed-Ulysses blog; BASELINE.md #4) —
converted to tokens/s at this model's FLOPs/token; the citation is emitted
in the JSON. The line also reports achieved model TFLOP/s and MFU against
the chip's bf16 peak.

The suite ``entries`` cover the driver's north-star milestone configs
(BASELINE.json): ZeRO-2 + FusedAdam BERT-large fp16, ZeRO-3 llama-style
(largest fitting 16G HBM single-chip), AutoTP-style inference generate,
FastGen paged/planned serving, MoE + Ulysses SP (dropless ragged dispatch),
the 1F1B pipeline (CPU mesh — one chip can't host a pipe axis), an
``autotune_smoke`` proving the tuner picks the headline config on-chip,
``comm_busbw_cpu_mesh_world8`` (non-degenerate collective busbw), and
``offload_param_memory`` (XLA memory_analysis evidence that the stage-3
fp32 master moves to host arguments). ``comm_bw`` records on-chip
collective bandwidth (degenerate busbw on 1 chip; real on a pod).

Timing uses ``engine.train_batches`` fused multi-step windows — one
dispatch per N optimizer steps, so per-dispatch host latency (~100 ms
through a remote-tunnel runtime) isn't billed to every step. The headline
also reports the MEASURED ``matmul_ceiling_tflops`` through this runtime
and ``vs_ceiling`` (round-2 verdict: ceiling claims must be
driver-verifiable).

Tuned defaults (measured on v5e, see PROFILE.md): micro-batch 32, remat=full,
Pallas flash attention 512/1024 blocks, bf16 head matmul with fp32
accumulation. BENCH_* env vars override; BENCH_SUITE=0 runs the headline
only; BENCH_CEILING=0 skips the ceiling measurement.

The output is schema v2 (``deepspeed_tpu/bench/schema.py``): a structured
``headline`` block + normalized per-entry ``{metrics, trace_phases,
memory, elapsed_s, skipped_reason}`` rows, validated before printing
(invalid output is a refusal, exit 1 — the r03–r05 ``"parsed": null``
failure mode is structurally closed). After printing, the result is
appended to ``bench_history/history.jsonl`` (``BENCH_RECORD=0`` skips)
and gated against the latest recorded round: a >5% headline or per-entry
regression exits 1 with phase attribution on stderr (``BENCH_GATE=0`` /
``BENCH_GATE_THRESHOLD=`` override; see README "Perf trajectory" and
``tools/bench-diff``).
"""
import gc
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "")

# global wall-clock budget (round-4 verdict #1: BENCH_r04 was rc=124 — the
# suite's entry-timeout caps summed to ~5h against a ~30min driver budget;
# a benchmark that cannot finish under its own judge has no numbers). Every
# entry runs under a deadline derived from the REMAINING budget; entries
# that don't fit emit explicit "skipped (budget)" rows; the JSON line always
# prints before the budget expires.
BENCH_T0 = time.monotonic()
BENCH_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 1500))
BENCH_RESERVE_S = 25.0          # kept back for the final JSON emission


def _remaining_budget() -> float:
    return BENCH_BUDGET_S - (time.monotonic() - BENCH_T0) - BENCH_RESERVE_S

# the reference's own best PUBLISHED sustained training rate (vs_baseline's
# referent everywhere in the JSON): ">175 TFlops/GPU (>54% of HW peak)" on
# A100s — DeepSpeed-Ulysses blog, reference blogs/deepspeed-ulysses/
# README.md:83 (BASELINE.md #4)
BASELINE_TFLOPS_CITED = 175.0

def _telemetry_section() -> dict:
    """The one "telemetry" config section every bench engine uses. Engine
    init reconfigures the PROCESS-WIDE tracer from its config section
    (last-engine-wins), so any entry whose config omitted these keys
    would silently disarm the --entry wrapper's tracer mid-entry and
    drop the row's trace_phases; measured-MFU stays opt-in because it
    prices a cost-analysis compile a timeout-bounded entry can't afford."""
    return {
        "measure_mfu": os.environ.get("BENCH_TELEMETRY_MFU", "0") != "0",
        "tracing": os.environ.get("BENCH_TRACING", "1") != "0",
        "trace_buffer_events": 8192,
    }


def chip_peak_tflops(device) -> float:
    """Peak bf16 TFLOP/s — ONE table shared with the telemetry train_mfu
    gauge (deepspeed_tpu/utils/chip_specs.py), v5e fallback."""
    from deepspeed_tpu.utils.chip_specs import chip_peak_tflops as _peak

    return _peak(getattr(device, "device_kind", ""), default=197.0)


def _active_params(cfg, n_params):
    """Params whose matmuls execute per token (MoE: top_k of n_experts)."""
    if cfg.n_experts > cfg.moe_top_k:
        ffn_mats = 3 if cfg.activation == "swiglu" else 2
        per_expert = ffn_mats * cfg.hidden_size * cfg.ffn_size
        n_params = n_params - cfg.num_layers * \
            (cfg.n_experts - cfg.moe_top_k) * per_expert
    return n_params


def _flops_per_token(cfg, n_params, seq_len):
    # 6*N_active per token (fwd+bwd matmuls) + causal-halved attention
    # 12*L*H*S*0.5; remat recompute is NOT counted (model FLOPs, not hardware)
    attn = 6 * cfg.num_layers * cfg.hidden_size * seq_len
    if not cfg.causal:
        attn *= 2
    return 6 * _active_params(cfg, n_params) + attn


def _hardware_flops_per_token(cfg, n_params, seq_len, remat):
    """Model FLOPs + the remat policy's recompute FLOPs — what the chip
    actually executes. ``vs_ceiling_hardware`` divides THIS by the measured
    matmul ceiling: with remat="full" the scanned body's forward runs twice
    (backward recompute), so model-FLOPs vs_ceiling is structurally capped
    at 6N/(6N+2N_body) ≈ 0.81 for GPT-2-125M — the round-3 "31% headroom"
    conflated the two accountings (the r4 remat sweep in PROFILE.md shows
    saving activations to avoid the recompute is memory-bound and LOSES)."""
    model = _flops_per_token(cfg, n_params, seq_len)
    if remat not in ("full", "save_nothing"):
        return model   # other policies: recompute varies; report model FLOPs
    # scanned-body ACTIVE params = active total minus everything outside the
    # layer scan: the vocab projection (once if tied, embedding+head if not;
    # the embedding lookup itself is a gather, not matmul FLOPs) and a
    # learned position table (absent under rope/alibi)
    vocab_tables = 1 if cfg.tie_embeddings else 2
    body = _active_params(cfg, n_params) \
        - vocab_tables * cfg.vocab_size * cfg.hidden_size \
        - (cfg.max_seq_len * cfg.hidden_size if cfg.pos_emb == "learned"
           else 0)
    attn_fwd = 2 * cfg.num_layers * cfg.hidden_size * seq_len  # fwd third
    if not cfg.causal:
        attn_fwd *= 2
    return model + 2 * body + attn_fwd


def measure_matmul_ceiling(n=8192, iters=100) -> float:
    """MEASURED pure-matmul ceiling for this chip through this runtime
    (tunnel transport included): chained bf16 [n,n]x[n,n] dots in one
    dispatch. This is the number ``vs_ceiling`` is checked against — the
    nominal datasheet peak is unreachable through a remote-execution
    tunnel (round-2 verdict asked for the ceiling to be driver-verifiable
    rather than asserted in prose)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.bfloat16)
    w = (jnp.eye(n, dtype=jnp.float32) * 1.0001).astype(jnp.bfloat16)

    @jax.jit
    def loop(x, w):
        def body(_, y):
            return (y @ w).astype(jnp.bfloat16)
        return jnp.sum(jax.lax.fori_loop(0, iters, body, x).astype(
            jnp.float32))

    float(loop(x, w))                                   # compile + warm
    best = float("inf")
    for _ in range(5):                 # best-of-N least-disturbed sample,
        t0 = time.perf_counter()       # like the headline's best-of-3
        float(loop(x, w))              # (5 here: each trial is ~0.8s cheap
        best = min(best, time.perf_counter() - t0)  # vs a ~8s train window)
    return 2 * n ** 3 * iters / best / 1e12


def train_bench(model, *, zero_stage, precision="bf16", optimizer="adam",
                batch, seq_len, gas, steps, attention="flash", remat="full",
                spec_kwargs=None, config_extra=None, note=None,
                optimizer_params=None, windows=3, warms=2,
                report_moe_drops=False):
    import jax

    import deepspeed_tpu as dst
    from deepspeed_tpu.models.transformer import PRESETS
    from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

    n_chips = jax.device_count()
    spec_kwargs = dict(spec_kwargs or {})
    if precision == "fp16":
        # the engine's fp16 flag scales the loss and casts the master copy;
        # the model's compute dtype must be switched too or matmuls stay bf16
        spec_kwargs.setdefault("dtype", "float16")
    spec = dst.causal_lm_spec(model, remat=remat, attention=attention,
                              **spec_kwargs)
    config = {
        "train_batch_size": batch * gas * n_chips,
        "train_micro_batch_size_per_gpu": batch,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": optimizer,
                      "params": dict(optimizer_params or {"lr": 1e-4})},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 10 ** 9,
    }
    if precision == "bf16":
        config["bf16"] = {"enabled": True}
    elif precision == "fp16":
        config["fp16"] = {"enabled": True, "initial_scale_power": 12}
    # bench rows embed a telemetry snapshot + trace phases; the row's own
    # mfu field stays the MFU source of record
    config["telemetry"] = _telemetry_section()
    config.update(config_extra or {})
    if os.environ.get("BENCH_OVERLAP", "1") == "0":
        # A/B switch for the bucketed overlap scheduler (README "Overlap
        # scheduler", docs/tutorials/overlap.md): the bucketed step is
        # numerics-identical, so two runs differing only in this knob
        # isolate the scheduler's wall-clock effect for bench-diff.
        # Applied AFTER config_extra — a row whose extra replaces the
        # zero_optimization section (the qgz row) must still honor the A/B
        config["zero_optimization"]["overlap_comm"] = False
    wire = os.environ.get("BENCH_WIRE", "").lower()
    if wire in ("exact", "qgz"):
        # A/B switch for the quantized wire (mirrors BENCH_OVERLAP; README
        # "Quantized wire", docs/tutorials/zeropp.md): BENCH_WIRE=exact
        # strips the ZeRO++ flags from every row, BENCH_WIRE=qgz forces
        # the full trio+LoCo on — two runs differing only in this knob
        # isolate the wire format's wall-clock/byte effect for bench-diff
        # (applied AFTER config_extra so the qgz row itself A/Bs too)
        zero_section = config["zero_optimization"]
        if wire == "exact":
            for key in ("zero_quantized_weights", "zero_quantized_gradients",
                        "loco_error_feedback"):
                zero_section[key] = False
        else:
            zero_section.update(zero_quantized_weights=True,
                                zero_quantized_gradients=True,
                                loco_error_feedback=True)
    elif wire:
        raise ValueError(f"BENCH_WIRE must be exact|qgz, got {wire!r}")
    if os.environ.get("BENCH_STEP_OVERLAP", "1") == "0":
        # A/B switch for the step-phase overlap (bucketed update +
        # double-buffered params; README "Overlap scheduler"): the
        # transform is numerics-identical, so two runs differing only in
        # this knob isolate its wall-clock effect for bench-diff.
        # Applied AFTER config_extra, like BENCH_OVERLAP/BENCH_WIRE — a
        # row whose extra replaces the zero section still honors the A/B
        config["zero_optimization"]["overlap_step"] = False
    engine, *_ = dst.initialize(model=spec, config=config)
    cfg = PRESETS[model]
    data = synthetic_lm_data(batch * n_chips, seq_len, cfg.vocab_size, seed=0)
    # fused multi-step windows (engine.train_batches): N optimizer steps per
    # dispatch — per-dispatch host latency (~100ms through the tunnel) would
    # otherwise be billed to every step and understate the chip by ~25%
    for _ in range(max(1, warms)):             # compile + warm (same shape;
        loss = engine.train_batches(data, steps)   # 2nd warm settles the
        float(loss)                                # allocator/transport)
    # best of N timed windows: the remote-execution tunnel adds run-to-run
    # variance (~±3%) unrelated to the program; the best window is the
    # least-disturbed measurement (all samples emitted for transparency)
    samples = []
    for _ in range(windows):
        t0 = time.perf_counter()
        loss = engine.train_batches(data, steps)
        float(loss)
        samples.append(time.perf_counter() - t0)
    dt = min(samples)
    tokens = steps * gas * batch * n_chips * seq_len
    tps_chip = tokens / dt / n_chips
    achieved = _flops_per_token(cfg, spec.num_params, seq_len) * tps_chip / 1e12
    hw = _hardware_flops_per_token(cfg, spec.num_params, seq_len,
                                   remat) * tps_chip / 1e12
    peak = chip_peak_tflops(jax.devices()[0])
    # round-4 verdict paper-cut (d): the MoE drop-monitor fraction belongs
    # in the bench row, not just the engine log (under EP the "dropless"
    # ragged path is only dropless per destination shard)
    moe_drop_frac = getattr(engine, "_moe_drop_frac", 0.0)
    # schema v2.1: the compiled-collective ledger totals + overlap estimate
    # ride next to trace_phases in every train row, so quantized-collective
    # rounds diff WIRE BYTES, not just tokens/s (README "Execution
    # observatory"). A ledger failure must not cost the measured row.
    # Ledgered BEFORE the snapshot: the lowering seeds the MFU flops cache
    # so the scrape below doesn't pay a second compile of the same step.
    comms_block = {}
    try:
        from deepspeed_tpu.profiling.observatory import bench_comms_block

        # the ledger legs are one-step quantities: hand the estimator the
        # measured per-step wall (best window / steps), at the seq the
        # window actually trained
        comms_block = bench_comms_block(engine, wall_s=dt / steps,
                                        seq_len=seq_len)
    except Exception as e:
        print(f"bench: collective ledger unavailable for this entry "
              f"({type(e).__name__}: {e})", file=sys.stderr)
    # schema: per-entry compiled-program memory legs next to the host
    # RSS + PJRT allocator stats the --entry wrapper adds — bench-diff
    # treats memory.* lower-is-better, so a temp-bytes blowup in the
    # lowered step diffs like a speed regression. Reads the SAME cached
    # lowering as the comms block above (no extra compile); a failure
    # costs a stderr note, never the measured row.
    mem_analysis_block = {}
    try:
        from deepspeed_tpu.autotuning.memory_model import (
            peak_bytes_from_stats,
        )
        from deepspeed_tpu.profiling.observatory import ledger_for_engine

        _, mem_stats = ledger_for_engine(engine, fold=False,
                                         seq_len=seq_len)
        if mem_stats:
            peak = peak_bytes_from_stats(mem_stats)
            if peak is not None:
                mem_analysis_block["device_peak_bytes"] = int(peak)
            temp = mem_stats.get("temp_size_in_bytes")
            if temp is not None:
                mem_analysis_block["temp_bytes"] = int(temp)
    except Exception as e:
        print(f"bench: memory_analysis unavailable for this entry "
              f"({type(e).__name__}: {e})", file=sys.stderr)
    # hlolint gate (mirrors BENCH_DSLINT, compiled-program edition): a
    # round whose LOWERED step violates its contract is refused, not
    # recorded — the lint reuses the ledger lowering cached just above,
    # so a clean step costs nothing extra. Raising here turns the row
    # into an explicit error row (the --entry wrapper's contract).
    _hlolint_entry_gate(engine, seq_len)
    # memlint gate (the memory-side sibling): donation/aliasing,
    # residency, and the committed memory contract over the same cached
    # lowering. BENCH_MEMLINT=0 opts out; BENCH_MEMLINT_CONTRACT pins.
    _memlint_entry_gate(engine, seq_len)
    # price the scrape-time gauges (tokens/s from the fenced window, measured
    # MFU via XLA cost analysis) while the engine is still alive — the
    # --entry wrapper then embeds the full snapshot in this row's JSON
    try:
        from deepspeed_tpu import telemetry

        telemetry.snapshot()
    except Exception:
        pass
    del engine
    gc.collect()
    out = {
        "tokens_per_sec_chip": round(tps_chip, 1),
        "model_tflops_per_sec_chip": round(achieved, 1),
        "hardware_tflops_per_sec_chip": round(hw, 1),
        "mfu": round(achieved / peak, 3),
        "loss": round(float(loss), 4),
        "window_samples_tokens_per_sec": [
            round(tokens / s / n_chips, 1) for s in samples],
    }
    if report_moe_drops:
        out["moe_dropped_frac"] = round(float(moe_drop_frac), 5)
    out.update(comms_block)
    if mem_analysis_block:
        # the --entry wrapper MERGES its host-RSS/PJRT stats into this
        # block (the engine is gone by the time the wrapper runs)
        out["memory"] = mem_analysis_block
    if note:
        out["note"] = note
    return out


def inference_bench(model="gpt2_125m", batch=8, prompt_len=128, max_new=128):
    """AutoTP-style inference generate (driver config #4): decode throughput."""
    import numpy as np

    import deepspeed_tpu as dst

    engine = dst.init_inference(model, dtype="bfloat16")
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 50000, prompt_len).tolist() for _ in range(batch)]
    out = engine.generate(prompts, max_new_tokens=max_new)  # compile + warm
    t0 = time.perf_counter()
    trials = 3
    for _ in range(trials):
        out = engine.generate(prompts, max_new_tokens=max_new)
    dt = (time.perf_counter() - t0) / trials
    del engine
    gc.collect()
    return {
        "decode_tokens_per_sec": round(batch * max_new / dt, 1),
        "batch": batch, "prompt_len": prompt_len, "max_new": max_new,
    }


def fastgen_bench(model="gpt2_125m", n_seqs=16, max_new=48):
    """FastGen-class serving (paged KV + SplitFuse + grouped-prefill planned
    scan + fused decode tail — ONE dispatch for the whole mixed workload).
    Emits the prefill/decode phase split the round-3 verdict asked for.
    The v1-slot-engine comparison (speedup_vs_slot, r3-measured ~3x) runs
    only under BENCH_LONG=1 — it doubles the entry's compile load for a
    comparison whose result is already a committed artifact."""
    import jax
    import numpy as np

    from deepspeed_tpu.inference.fastgen import FastGenEngine
    from deepspeed_tpu.inference.ragged import RaggedInferenceEngine

    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(16, 480, n_seqs)]
    prompts = [rng.integers(0, 50000, n).tolist() for n in lens]
    uids = list(range(n_seqs))

    fg = FastGenEngine(model, n_blocks=512, block_size=64,
                       max_blocks_per_seq=16, token_budget=512,
                       temperature=0.0, seed=0, max_seq_len=1024)
    # warm at FULL shape: the planned-serve and decode-scan tiers are
    # max_new-dependent; a short warm run leaves them cold and the timed
    # run pays their compiles
    fg.generate_all(uids, prompts, max_new_tokens=max_new)
    t0 = time.perf_counter()
    out = fg.generate_all(uids, prompts, max_new_tokens=max_new)
    t_fg = time.perf_counter() - t0
    gen = sum(len(v) for v in out.values())

    # phase split (separate dispatches so each phase is timeable): prefill-
    # only planned scan, then decode-only windows. First cycle warms the
    # unfused program shapes, second is timed.
    t_prefill = t_decode = gen_decode = 0
    for timed in (False, True):
        cyc = [(1000 if timed else 100) + u for u in uids]
        t0 = time.perf_counter()
        fg.put(cyc, prompts)
        assert fg.serve_planned(max_new, until_prefilled=True,
                                fuse_decode_tail=False), \
            "plan infeasible — phase split would time the wrong phases"
        jax.block_until_ready(jax.tree.leaves(fg.pool)[0])
        t_prefill = time.perf_counter() - t0
        gen_planned = sum(len(fg.seqs[u].generated) for u in cyc)
        t0 = time.perf_counter()
        fg._generate_dynamic(cyc, max_new)
        jax.block_until_ready(jax.tree.leaves(fg.pool)[0])
        t_decode = time.perf_counter() - t0
        gen_decode = sum(len(fg.seqs[u].generated) for u in cyc) - gen_planned
        fg.flush(cyc)
    del fg

    res = {
        "decode_tokens_per_sec": round(gen / t_fg, 1),
        "decode_only_tokens_per_sec": round(gen_decode / t_decode, 1),
        "prefill_tokens_per_sec": round(sum(lens) / t_prefill, 1),
        "prefill_phase_s": round(t_prefill, 3),
        "decode_phase_s": round(t_decode, 3),
        "n_seqs": n_seqs, "prompt_lens": "16-480", "max_new": max_new,
    }
    if os.environ.get("BENCH_LONG", "0") != "0":
        slot = RaggedInferenceEngine(model, max_slots=n_seqs, max_len=1024,
                                     temperature=0.0, seed=0)
        slot.generate_all(uids, prompts, max_new_tokens=max_new)  # warm
        t0 = time.perf_counter()
        out = slot.generate_all(uids, prompts, max_new_tokens=max_new)
        t_slot = time.perf_counter() - t0
        gen_slot = sum(len(v) for v in out.values())
        del slot
        res["slot_engine_tokens_per_sec"] = round(gen_slot / t_slot, 1)
        res["speedup_vs_slot"] = round((gen / t_fg) / (gen_slot / t_slot), 2)
    gc.collect()
    return res


def fastgen_sla_bench(model="gpt2_125m", n_req=24, max_new=48,
                      loads=None):
    """Arrival-process serving evaluation (round-3 verdict Missing #5): the
    reference's FastGen benchmarks measure throughput UNDER client SLAs
    (blogs/deepspeed-fastgen/README.md:133-163 — Poisson arrivals, TTFT +
    per-token latency percentiles), not just closed-batch throughput.

    Poisson arrivals at ``load`` x the engine's measured decode capacity;
    the serve loop admits due requests, runs one SplitFuse tick while any
    prefill is pending, else a short fused decode window. Reported per
    load: achieved tok/s, TTFT p50/p95, per-output-token latency p50/p95,
    e2e p95. TTFT through a remote-execution tunnel carries the ~100 ms
    per-dispatch constant — real for THIS runtime, not a chip property."""
    import numpy as np

    from deepspeed_tpu.inference.fastgen import FastGenEngine

    # default: the interesting (near-capacity) load only; BENCH_LONG adds
    # the light-load point — each load costs a full warm+timed trace pair
    if loads is None:
        loads = (0.5, 0.9) if os.environ.get("BENCH_LONG", "0") != "0" \
            else (0.9,)
    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(16, 360, n_req)]
    prompts = [rng.integers(0, 50000, n).tolist() for n in lens]

    fg = FastGenEngine(model, n_blocks=512, block_size=64,
                       max_blocks_per_seq=16, token_budget=512,
                       temperature=0.0, seed=0, max_seq_len=1024)
    # capacity probe (warm pass first — the tier programs compile lazily)
    fg.generate_all(list(range(16)), prompts[:16], max_new_tokens=max_new)
    t0 = time.perf_counter()
    fg.generate_all([100 + u for u in range(16)], prompts[:16],
                    max_new_tokens=max_new)
    cap_tps = 16 * max_new / (time.perf_counter() - t0)

    def serve_trace(lam, arrival, uids, record):
        first_tok, done_at, n_out = {}, {}, {}
        pending = list(zip(arrival, uids, prompts))
        t0 = time.perf_counter()

        def note(emitted):
            now = time.perf_counter() - t0
            for uid, toks in emitted.items():
                cnt = len(toks) if isinstance(toks, list) else 1
                # the post-break reconciliation can replay tokens already
                # counted — clamp so n_out never exceeds max_new (an
                # overcount deflates the per-token latency percentiles)
                cnt = min(cnt, max_new - n_out.get(uid, 0))
                if cnt:
                    first_tok.setdefault(uid, now)
                n_out[uid] = n_out.get(uid, 0) + cnt
                # a flushed uid can reappear once (the closed stream's
                # in-flight window) — completion time must not move
                if n_out[uid] >= max_new and uid not in done_at:
                    done_at[uid] = now
                    fg.flush([uid])

        while len(done_at) < n_req:
            now = time.perf_counter() - t0
            while pending and pending[0][0] <= now and fg.can_schedule():
                _, uid, pr = pending.pop(0)
                fg.put([uid], [pr])
            if not fg.seqs:
                time.sleep(min(0.005, max(0.0, pending[0][0] - now)))
                continue
            if any(s.prefill_remaining > 0 for s in fg.seqs.values()):
                note(fg.step())
            else:
                # async double-buffered decode (engine.decode_stream):
                # window N+1 runs on device while N drains; break out when
                # the next arrival is due so admission latency stays bounded
                served = False
                for emitted in fg.decode_stream(window=8):
                    served = True
                    note(emitted)
                    now = time.perf_counter() - t0
                    if pending and pending[0][0] <= now:
                        break
                # early break closes the stream mid-flight; its last window
                # drains into engine state without being yielded — reconcile
                note({uid: s.generated[n_out.get(uid, 0):]
                      for uid, s in list(fg.seqs.items())
                      if len(s.generated) > n_out.get(uid, 0)})
                if not served:
                    # no ladder rung fits (headroom < 8 near max_len, or
                    # block exhaustion): single-tick fallback, same as
                    # _generate_dynamic's — without it this loop busy-spins
                    emitted = fg.step()
                    note(emitted)
                    if not emitted:       # truly stuck — don't spin forever
                        for uid in list(fg.seqs):
                            done_at.setdefault(uid,
                                               time.perf_counter() - t0)
                            first_tok.setdefault(uid, done_at[uid])
                            fg.flush([uid])
        if not record:
            return None
        tts = sorted(first_tok[u] - arrival[i] for i, u in enumerate(uids))
        ptl = sorted((done_at[u] - first_tok[u]) / max(1, n_out[u] - 1)
                     for u in uids)
        e2e = sorted(done_at[u] - arrival[i] for i, u in enumerate(uids))
        span = max(done_at.values())
        return {
            "offered_req_per_s": round(lam, 2),
            "achieved_tokens_per_sec": round(sum(n_out.values()) / span, 1),
            "ttft_p50_s": round(tts[len(tts) // 2], 3),
            "ttft_p95_s": round(tts[int(len(tts) * 0.95)], 3),
            "tpot_p50_s": round(ptl[len(ptl) // 2], 4),
            "tpot_p95_s": round(ptl[int(len(ptl) * 0.95)], 4),
            "e2e_p95_s": round(e2e[int(len(e2e) * 0.95)], 3),
        }

    out = {"capacity_probe_tokens_per_sec": round(cap_tps, 1)}
    for load in loads:
        # offered load in requests/s, scaled off the DECODE capacity probe
        # (prefill work rides the same budget — loads > ~0.9 oversubscribe)
        lam = load * cap_tps / max_new
        arrival = np.cumsum(rng.exponential(1.0 / lam, n_req))
        # identical trace twice: pass 1 compiles every slot/window tier the
        # trace hits (lazy tier programs would otherwise land in the timed
        # percentiles), pass 2 is measured
        for record in (False, True):
            base = int(1000 * load) + (0 if record else 500)
            res = serve_trace(lam, arrival, [base + i for i in range(n_req)],
                              record)
        out[f"load_{load}"] = res
    del fg
    gc.collect()
    return out


def fleet_sla_bench(model="gpt2_125m", n_req=12, max_new=12,
                    n_replicas=3):
    """Poisson SLA bench against a REPLICA FLEET with a mid-burst replica
    kill (the fleet analog of ``fastgen_sla_poisson_gpt2``, which stays
    in the suite as the single-replica diff referent).

    Three frontends over three FastGen engines SHARING one parameter
    tree (one model in host memory, three KV pools) behind a
    ``FleetRouter``; Poisson arrivals are offered at 2× ONE replica's
    measured capacity, and a third of the way into the burst one replica
    is chaos-killed (every tick raises → its circuit opens → in-flight
    work fails over). Reported: p50/p99 TTFT for surviving traffic,
    terminal-outcome counts, failover count, and ``requests_lost`` —
    the count of uids that reached NO terminal state, which the fleet's
    zero-loss guarantee pins at 0.

    With the fleet observatory attached (default; ``BENCH_SLO=0``
    disables, mirroring BENCH_OVERLAP) the row also embeds a
    schema-v2.6 ``slo`` block: burn-rate verdicts per objective and the
    goodput/wasted token reconciliation — ``fleet-report <file>``
    renders it."""
    import jax
    import numpy as np

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.fastgen import FastGenEngine
    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.serving.fleet import FleetRouter
    from deepspeed_tpu.serving.observatory import slo_bench_block
    from deepspeed_tpu.testing import chaos

    # A/B switch for the SLO/observatory layer: two runs differing only
    # in this knob isolate its (intended-zero) hot-path cost
    want_slo = os.environ.get("BENCH_SLO", "1") != "0"
    slo_cfg = {"objectives": [
        {"name": "fleet_ttft", "metric": "ttft_p99_s",
         "threshold_s": 10.0, "target": 0.99},
        {"name": "availability", "metric": "availability",
         "target": 0.95},
    ]} if want_slo else None

    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(16, 96, n_req)]
    prompts = [rng.integers(0, 50000, n).tolist() for n in lens]

    cfg = T.get_model_config(model, max_seq_len=512)
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    engines = [FastGenEngine(cfg, params=params, n_blocks=128,
                             block_size=32, max_blocks_per_seq=8,
                             token_budget=128, temperature=0.0, seed=0)
               for _ in range(n_replicas)]
    # replicas of the SAME model/config share ONE compiled-tick cache:
    # the tick closures capture only cfg + sampling knobs (identical
    # here), params/pool are arguments — so the fleet pays each
    # (bucket, mb-tier) program's XLA compile once, not once per replica
    for eng in engines[1:]:
        eng._ticks = engines[0]._ticks
    fleet = FleetRouter.build(
        engines,
        serving_config={"max_queue": 16,
                        "default_max_new_tokens": max_new,
                        "circuit_failure_threshold": 2,
                        "circuit_backoff_s": 0.2,
                        "circuit_backoff_max_s": 2.0},
        fleet_config={"min_ready_replicas": 2, "max_attempts": 4,
                      "retry_backoff_s": 0.05, "retry_backoff_max_s": 0.5},
        slo_config=slo_cfg)
    try:
        # warm the exact tick programs the fleet drives (step-path only —
        # generate_all's fused decode scans never run under run_tick);
        # the shared cache makes replicas 1..N-1 free
        for i, fe in enumerate(fleet.replicas()):
            fe.submit(900 + i, prompts[0][:90], max_new_tokens=max_new)
            fe.run_until_drained(5_000, deadline_s=180.0)
        # single-replica capacity probe, served the same way the fleet
        # serves (mixed SplitFuse ticks)
        fe0 = fleet.replicas()[0]
        for i in range(4):
            fe0.submit(500 + i, prompts[i], max_new_tokens=max_new)
        t0 = time.perf_counter()
        fe0.run_until_drained(20_000, deadline_s=180.0)
        cap_tps = 4 * max_new / (time.perf_counter() - t0)

        lam = 2.0 * cap_tps / max_new       # 2× one replica, in req/s
        arrival = np.cumsum(rng.exponential(1.0 / lam, n_req))
        kill_at = float(arrival[n_req // 3])
        uids = [1000 + i for i in range(n_req)]
        first_tok, done_at, states = {}, {}, {}
        submitted = set()
        pending = list(zip(arrival, uids, prompts))
        killed_name = None
        t0 = time.perf_counter()
        while len(done_at) < n_req and time.perf_counter() - t0 < 300.0:
            now = time.perf_counter() - t0
            if killed_name is None and now >= kill_at:
                killed_name = fleet.replicas()[0].name
                chaos.arm(f"serving/tick@{killed_name}=fail:1000000")
            while pending and pending[0][0] <= now:
                _, uid, pr = pending.pop(0)
                fleet.submit(uid, pr, max_new_tokens=max_new)
                submitted.add(uid)
            fleet.run_tick()
            now = time.perf_counter() - t0
            for uid in submitted:
                if uid in done_at:
                    continue
                res = fleet.result(uid)
                if res.tokens and uid not in first_tok:
                    first_tok[uid] = now
                if res.state != "active":
                    states[uid] = res.state
                    done_at[uid] = now
            if pending and not fleet.active_count():
                time.sleep(max(0.0, min(0.005, pending[0][0] - now)))
        # snapshot the observatory BEFORE close (shutdown force-fails
        # would re-attribute any straggler's tokens as evicted waste)
        slo_block = slo_bench_block(fleet) if want_slo else None
    finally:
        chaos.disarm()
        fleet.close()
    del engines, params
    gc.collect()

    completed = [u for u, s in states.items() if s == "completed"]
    tts = sorted(first_tok[u] - arrival[u - 1000] for u in completed
                 if u in first_tok)
    counts = {}
    for s in states.values():
        counts[s] = counts.get(s, 0) + 1
    failovers = sum(
        telemetry.counter("fleet_failovers_total").value(reason=r)
        for r in ("replica_hung", "circuit_open", "drain", "shed",
                  "failed", "rejected"))
    out = {
        "replicas": n_replicas,
        "replica_killed_mid_burst": killed_name or "none",
        "capacity_probe_tokens_per_sec": round(cap_tps, 1),
        "offered_x_single_replica_capacity": 2.0,
        "requests": n_req,
        "submitted": len(submitted),
        "completed": len(completed),
        "failovers": int(failovers),
        # the zero-loss guarantee: every submitted uid reached exactly
        # one terminal state
        "requests_lost": len(submitted) - len(states),
        "single_replica_referent": "fastgen_sla_poisson_gpt2",
    }
    if slo_block is not None:
        out["slo"] = slo_block
    for s, n in sorted(counts.items()):
        if s != "completed":
            out[f"outcome_{s}"] = n
    if tts:
        out["ttft_p50_s"] = round(tts[len(tts) // 2], 3)
        out["ttft_p99_s"] = round(tts[min(len(tts) - 1,
                                          int(len(tts) * 0.99))], 3)
    return out


def fleet_sla_multitenant_bench(model="gpt2_125m", n_req=18, max_new=12,
                                n_replicas=3):
    """Multi-tenant QoS bench: the fleet SLA scenario with one batch-tier
    tenant flooding ~10× the others while a realtime and a standard
    tenant send background traffic.

    Same fleet shape as ``fleet_sla_poisson_gpt2`` (3 replicas, one
    shared parameter tree, Poisson arrivals, mid-burst replica kill) but
    every request carries a tenant: ``hot`` (batch tier, rate-capped)
    draws ~10x the traffic of ``rt`` (realtime) and ``std`` (standard).
    The hot tenant's excess resolves to structured tenant-scoped
    rejections; the others keep completing. Reports a schema-v2.5
    ``tenants`` block — per-tenant submitted / terminal-outcome counts
    (pulled from the fleet's own ``fleet_tenant_*`` counters, so the row
    IS the accounting the reconciliation invariant pins) plus per-tenant
    TTFT p50/p99 — and the fleet-wide ``requests_lost`` zero-loss pin.

    With the observatory attached (``BENCH_SLO=0`` disables) the row
    also embeds a schema-v2.6 ``slo`` block whose objectives include a
    TENANT-scoped TTFT (the realtime tenant) — burn verdicts prove the
    flooder's excess never spent the realtime tenant's error budget."""
    import jax
    import numpy as np

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.fastgen import FastGenEngine
    from deepspeed_tpu.models import transformer as T
    from deepspeed_tpu.serving.fleet import FleetRouter
    from deepspeed_tpu.serving.observatory import slo_bench_block
    from deepspeed_tpu.testing import chaos

    want_slo = os.environ.get("BENCH_SLO", "1") != "0"
    slo_cfg = {"objectives": [
        {"name": "rt_ttft", "metric": "ttft_p99_s", "tenant": "rt",
         "threshold_s": 10.0, "target": 0.99},
        {"name": "availability", "metric": "availability",
         "target": 0.95},
    ]} if want_slo else None

    rng = np.random.default_rng(0)
    lens = [int(x) for x in rng.integers(16, 96, n_req)]
    prompts = [rng.integers(0, 50000, n).tolist() for n in lens]
    tenant_names = ["rt", "std", "hot"]
    tenants = [str(t) for t in rng.choice(tenant_names, n_req,
                                          p=[1 / 12, 1 / 12, 10 / 12])]

    cfg = T.get_model_config(model, max_seq_len=512)
    params = T.init_params(cfg, jax.random.PRNGKey(0))
    engines = [FastGenEngine(cfg, params=params, n_blocks=128,
                             block_size=32, max_blocks_per_seq=8,
                             token_budget=128, temperature=0.0, seed=0)
               for _ in range(n_replicas)]
    for eng in engines[1:]:
        eng._ticks = engines[0]._ticks
    fleet = FleetRouter.build(
        engines,
        serving_config={"max_queue": 16,
                        "default_max_new_tokens": max_new,
                        "circuit_failure_threshold": 2,
                        "circuit_backoff_s": 0.2,
                        "circuit_backoff_max_s": 2.0},
        fleet_config={"min_ready_replicas": 2, "max_attempts": 4,
                      "retry_backoff_s": 0.05, "retry_backoff_max_s": 0.5},
        tenancy_config={
            "tenants": {
                "rt": {"tier": "realtime"},
                "std": {"tier": "standard"},
                # the flooder: batch tier, hard-capped requests/s — its
                # excess must bounce with tenant-scoped retry-afters
                "hot": {"tier": "batch", "requests_per_s": 1.0,
                        "burst_requests": 3},
            }},
        slo_config=slo_cfg)
    try:
        for i, fe in enumerate(fleet.replicas()):
            fe.submit(900 + i, prompts[0][:90], max_new_tokens=max_new)
            fe.run_until_drained(5_000, deadline_s=180.0)
        fe0 = fleet.replicas()[0]
        for i in range(4):
            fe0.submit(500 + i, prompts[i], max_new_tokens=max_new)
        t0 = time.perf_counter()
        fe0.run_until_drained(20_000, deadline_s=180.0)
        cap_tps = 4 * max_new / (time.perf_counter() - t0)

        lam = 2.0 * cap_tps / max_new
        arrival = np.cumsum(rng.exponential(1.0 / lam, n_req))
        kill_at = float(arrival[n_req // 3])
        uids = [1000 + i for i in range(n_req)]
        first_tok, done_at, states = {}, {}, {}
        submitted = set()
        pending = list(zip(arrival, uids, prompts, tenants))
        killed_name = None
        t0 = time.perf_counter()
        while len(done_at) < n_req and time.perf_counter() - t0 < 300.0:
            now = time.perf_counter() - t0
            if killed_name is None and now >= kill_at:
                killed_name = fleet.replicas()[0].name
                chaos.arm(f"serving/tick@{killed_name}=fail:1000000")
            while pending and pending[0][0] <= now:
                _, uid, pr, ten = pending.pop(0)
                fleet.submit(uid, pr, max_new_tokens=max_new, tenant=ten)
                submitted.add(uid)
            fleet.run_tick()
            now = time.perf_counter() - t0
            for uid in submitted:
                if uid in done_at:
                    continue
                res = fleet.result(uid)
                if res.tokens and uid not in first_tok:
                    first_tok[uid] = now
                if res.state != "active":
                    states[uid] = res.state
                    done_at[uid] = now
            if pending and not fleet.active_count():
                time.sleep(max(0.0, min(0.005, pending[0][0] - now)))
        # fleet-side per-tenant accounting, straight from the counters
        sub_ctr = telemetry.counter("fleet_tenant_submitted_total")
        res_ctr = telemetry.counter("fleet_tenant_resolved_total")
        tenant_rows = {}
        for ten in tenant_names:
            outcomes = {}
            for state in ("completed", "expired", "failed", "rejected",
                          "shed"):
                n = int(res_ctr.value(tenant=ten, outcome=state))
                if n:
                    outcomes[state] = n
            row = {"submitted": int(sub_ctr.value(tenant=ten)),
                   "outcomes": outcomes}
            tts = sorted(
                first_tok[u] - arrival[u - 1000] for u, s in states.items()
                if s == "completed" and u in first_tok
                and tenants[u - 1000] == ten)
            if tts:
                row["ttft_p50_s"] = round(tts[len(tts) // 2], 3)
                row["ttft_p99_s"] = round(
                    tts[min(len(tts) - 1, int(len(tts) * 0.99))], 3)
            tenant_rows[ten] = row
        slo_block = slo_bench_block(fleet) if want_slo else None
    finally:
        chaos.disarm()
        fleet.close()
    del engines, params
    gc.collect()

    counts = {}
    for s in states.values():
        counts[s] = counts.get(s, 0) + 1
    out = {
        "replicas": n_replicas,
        "replica_killed_mid_burst": killed_name or "none",
        "capacity_probe_tokens_per_sec": round(cap_tps, 1),
        "requests": n_req,
        "submitted": len(submitted),
        "completed": counts.get("completed", 0),
        "requests_lost": len(submitted) - len(states),
        "hot_tenant_share": round(tenants.count("hot") / n_req, 2),
        "tenants": tenant_rows,
        "single_replica_referent": "fleet_sla_poisson_gpt2",
    }
    if slo_block is not None:
        out["slo"] = slo_block
    for s, n in sorted(counts.items()):
        if s != "completed":
            out[f"outcome_{s}"] = n
    return out


# prefix for CPU-mesh subprocess snippets: env alone is not enough where a
# sitecustomize registers a TPU PJRT plugin — pin the platform via config too
CPU_SNIPPET_PRELUDE = r'''
import jax
jax.config.update("jax_platforms", "cpu")
'''

PIPE_BENCH_SNIPPET = CPU_SNIPPET_PRELUDE + r'''
import json, time, itertools
import jax
import deepspeed_tpu as dst
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

def run(mesh_cfg, batch, steps=4, n_micro=None):
    mesh_mod.reset_mesh()
    spec = dst.causal_lm_spec("tiny", dtype="float32", num_layers=4,
                              hidden_size=128, num_heads=4, max_seq_len=128,
                              pipeline_micro_batches=n_micro)
    dp = mesh_cfg.get("data", 1)
    config = {"train_batch_size": batch, "train_micro_batch_size_per_gpu":
              batch // dp, "gradient_accumulation_steps": 1,
              "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
              "zero_optimization": {"stage": 0}, "mesh": mesh_cfg,
              "steps_per_print": 10 ** 9,
              "telemetry": _telemetry_section()}
    engine, *_ = dst.initialize(model=spec, config=config)
    data = itertools.repeat(next(synthetic_lm_data(batch, 128, 512, seed=0)))
    loss = engine.train_batch(data)          # compile
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(data)
    float(jax.device_get(loss))
    return steps * batch * 128 / (time.perf_counter() - t0)

# sweep pipe x microbatches (round-3 verdict: decompose the overhead).
# Work theory per device, in stage-row units: a 1F1B tick executes one
# stage forward + one vjp (fwd recompute + bwd ~ 3 fwd-equiv) on every
# tick of T = M + 2P - 2, valid or not (SPMD uniform program); useful work
# is M ticks' worth, and the flat baseline does 3 fwd-equiv with NO
# recompute -> work_ratio_theory = (T/M) * (4/3).
sweep = {}
for pipe, dp in ((2, 4), (4, 2)):
    for m in (2, 4, 8):
        tps = run({"pipe": pipe, "data": dp}, 64, n_micro=m)
        T = m + 2 * pipe - 2
        sweep[f"pipe{pipe}xdata{dp}_m{m}"] = {
            "tokens_per_sec": round(tps, 1),
            "bubble_theory": round((pipe - 1) / (m + pipe - 1), 3),
            "work_ratio_theory": round((T / m) * 4 / 3, 2)}
tps_flat = run({"data": 8}, 64)
best_key, best = max(sweep.items(),
                     key=lambda kv: kv[1]["tokens_per_sec"])

# per-tick fixed cost (CPU-mesh artifact): at fixed pipe, t_step(M) =
# T(M) * (fixed + work(M)) with work per tick ~ rows/M. Solve from the
# pipe2 M=2 and M=8 points; the on-TPU expectation zeroes `fixed` (one
# compiled program, ppermute ~us on ICI), leaving work_ratio_theory as
# the whole expected overhead.
tok = 64 * 128
t2 = tok / sweep["pipe2xdata4_m2"]["tokens_per_sec"]   # T=4
t8 = tok / sweep["pipe2xdata4_m8"]["tokens_per_sec"]   # T=10
# t2 = 4a + 4*(R/2)w ; t8 = 10a + 10*(R/8)w  (R rows per device)
# -> t2 = 4a + 2Rw ; t8 = 10a + 1.25Rw
a = (t2 * 1.25 - t8 * 2) / (4 * 1.25 - 10 * 2)
fixed_share = max(0.0, min(1.0, a * 10 / t8))
print(json.dumps({
    "best_config": best_key,
    "best_tokens_per_sec": best["tokens_per_sec"],
    "data8_tokens_per_sec": round(tps_flat, 1),
    "overhead_factor": round(tps_flat / best["tokens_per_sec"], 2),
    "per_tick_fixed_s_cpu_mesh": round(a, 4),
    "fixed_cost_share_of_best": round(fixed_share, 3),
    "on_tpu_expected_overhead": best["work_ratio_theory"],
    "sweep": sweep}))
'''


def pipeline_bench():
    """1F1B pipeline cost vs the flat-data-parallel step on the
    8-virtual-device CPU mesh (a single real chip can't host a pipe axis),
    with the round-3-requested decomposition: a pipe x microbatch sweep,
    the analytic bubble and executed/useful work ratios per config, and
    the per-tick FIXED cost solved from the M-scaling at fixed pipe — the
    CPU-mesh artifact (per-iteration thread dispatch + software
    collectives) that an on-TPU run would not pay. ``overhead_factor`` =
    flat tok/s / best pipe tok/s; ``on_tpu_expected_overhead`` is the
    work-ratio theory for the best config (the schedule's real cost:
    fill/drain rectangle x the 1F1B stage recompute vs a no-remat flat
    step). Absolute CPU-mesh tok/s are NOT chip numbers."""
    out = _run_cpu_world8(PIPE_BENCH_SNIPPET, timeout=2400)
    return out[0] if isinstance(out, list) else out


def autotune_smoke():
    """The autotuner MEASURES candidates on-chip and must pick the headline
    micro-batch (round-2 verdict: the tuner's choice was asserted in prose,
    never evidenced in the bench JSON)."""
    import deepspeed_tpu as dst
    from deepspeed_tpu.autotuning.autotuner import Autotuner

    spec = dst.causal_lm_spec("gpt2_125m", remat="full", attention="flash")
    base = {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 32,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 1}, "bf16": {"enabled": True},
            "steps_per_print": 10 ** 9}
    tuner = Autotuner(spec, base, seq_len=1024, vocab_size=50257,
                      steps=2, warmup=1)
    # 256 is analytically infeasible on 16G HBM — it must be pruned by the
    # memory model WITHOUT compiling (the model's selling point: round-3
    # verdict flagged that no driver-visible run ever pruned anything)
    best = tuner.tune(micro_batches=[8, 16, 32, 256], zero_stages=[1],
                      remats=["full"])
    mb = best.config.get("train_micro_batch_size_per_gpu")
    return {
        "picked_micro_batch": mb,
        # the tuner's internal relative measure (async-dispatch timing) —
        # used for RANKING candidates, not calibrated absolute throughput
        "tuner_score": round(best.throughput, 2),
        "measured_candidates": len(tuner.results),
        "pruned_by_memory_model": len(tuner.pruned),
        "picks_headline_micro_batch": mb == 32,
    }


def autotune_plan_roundtrip():
    """The PLAN engine (autotuning/planner.py) end to end on THIS
    backend: enumerate the overlap-knob space, analytically refuse the
    canary through memlint's oom-preflight, rank by analytic price, cache
    the plan, and prove a fresh engine initialize LOADS it (cache-hit
    counter +1, planned knobs applied). Dry-run pricing only — the
    per-candidate lowering leg is the tools/plan CLI's job; this row
    evidences the cache round-trip every training run depends on."""
    import tempfile

    import jax

    import deepspeed_tpu as dst
    from deepspeed_tpu.autotuning.planner import (PlanEngine, plan_path,
                                                  write_plan)
    from deepspeed_tpu.comm import mesh as mesh_mod

    spec = dst.causal_lm_spec("tiny", dtype="float32", max_seq_len=32)
    base = {"train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "mesh": {"data": jax.device_count()},
            "steps_per_print": 10 ** 9}
    cache_dir = tempfile.mkdtemp(prefix="bench_plan_")
    planner = PlanEngine(spec, base, seq_len=32)
    doc = planner.run(dry_run=True)
    write_plan(plan_path(cache_dir, doc["key"]), doc)
    mesh_mod.reset_mesh()
    engine, *_ = dst.initialize(model=spec, config={
        **base, "autotuning": {"enabled": True,
                               "plan_cache_dir": cache_dir}})
    pred = doc.get("predicted") or {}
    return {
        "candidates": len(doc["candidates"]),
        "oom_refused": doc["counters"]["oom_refused"],
        "priced": doc["counters"]["priced"],
        "winner_pred_step_ms": round(
            (pred.get("total_s") or 0.0) * 1e3, 4),
        "plan_cache_hit_roundtrip": engine._plan_status == "hit",
    }


def _run_cpu_world8(snippet: str, timeout: int = 900):
    """Run a snippet in a subprocess on the 8-virtual-device CPU mesh and
    parse its last stdout line as JSON (error row on failure)."""
    import json as _json
    import subprocess

    from deepspeed_tpu.utils.xla_compat import cpu_collective_timeout_flags

    env = dict(os.environ,
               JAX_PLATFORMS="cpu", DSTPU_ACCELERATOR="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          # 8 virtual device threads time-slice ONE core on
                          # this box: the default 20s/40s collective
                          # rendezvous deadlines flake on long fused
                          # programs (observed: F rendezvous.cc:127 aborts
                          # mid-2k-step runs) — raise them far past any
                          # legitimate scheduling delay, where this jaxlib
                          # knows the flags (probed: unknown XLA_FLAGS
                          # hard-abort backend init)
                          + cpu_collective_timeout_flags()),
               PYTHONPATH=os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", snippet],
                         capture_output=True, text=True, env=env,
                         timeout=timeout)
    if out.returncode != 0 or not out.stdout.strip():
        return [{"error": (out.stderr or "no output")[-400:]}]
    try:
        return _json.loads(out.stdout.strip().splitlines()[-1])
    except ValueError:
        return [{"error": (out.stderr or out.stdout)[-400:]}]


STABILITY_SNIPPET = CPU_SNIPPET_PRELUDE + r'''
import itertools, json, os
import numpy as np
import jax
import deepspeed_tpu as dst
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

STEPS = int(os.environ.get("BENCH_STABILITY_STEPS", 500))
WINDOW = 100

def curve(zero_cfg):
    mesh_mod.reset_mesh()
    # fp32 compute: XLA's CPU AllReducePromotion pass CHECK-fails on some
    # bf16 collective patterns (same reason the driver dryrun's second mesh
    # runs fp32); the wire formats under test (int8 qgZ, LoCo residuals)
    # are precision-independent
    spec = dst.causal_lm_spec("tiny", dtype="float32", num_layers=2,
                              max_seq_len=64)
    config = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
              "gradient_accumulation_steps": 1,
              "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
              "zero_optimization": zero_cfg,
              "steps_per_print": 10 ** 9,
              "telemetry": _telemetry_section()}
    engine, *_ = dst.initialize(model=spec, config=config)
    # 16-batch corpus cycled: loss must DECREASE (memorization) without
    # NaN/drift over the full horizon — the long-run state-corruption
    # check the nightly convergence suites do in the reference
    corpus = [b for b, _ in zip(synthetic_lm_data(8, 64, 512, seed=0),
                                range(16))]
    losses = []
    for w in range(STEPS // WINDOW):
        data = itertools.cycle(corpus)
        loss = engine.train_batches(data, WINDOW)
        losses.append(round(float(loss), 4))
    return losses

runs = {
    "zero3_offload_param": {"stage": 3, "offload_param": {"device": "cpu"}},
    "zero2_qgz_loco": {"stage": 2, "zero_quantized_gradients": True,
                        "loco_error_feedback": True},
    "exact_zero2": {"stage": 2},
}
out = {}
for name, zc in runs.items():
    ls = curve(zc)
    out[name] = {"first": ls[0], "last": ls[-1],
                 "min": min(ls), "max": max(ls),
                 "finite": all(np.isfinite(ls)),
                 "monotone_trend": ls[-1] < ls[0] - 1.0,
                 "curve_every_100": ls}
ex = out["exact_zero2"]["last"]
out["final_loss_max_abs_dev_vs_exact"] = round(max(
    abs(out["zero3_offload_param"]["last"] - ex),
    abs(out["zero2_qgz_loco"]["last"] - ex)), 4)
out["steps"] = STEPS
print(json.dumps(out))
'''


def stability_2k():
    """Long-horizon stability artifact (round-3 verdict Missing #4): 2k
    optimizer steps on the 8-device CPU mesh for the exotic state-carrying
    modes — ZeRO-3 + offload_param (host master streamed per step) and
    qgZ + LoCo (int8 wire + error feedback residuals) — vs the exact
    engine. Asserts: finite everywhere, decreasing trend, final loss within
    tolerance of exact. The per-100-step curve ships in the JSON.

    Suite default is 500 steps: bench budget, AND an XLA:CPU runtime defect
    found by the longer runs — after ~1k executions of collective-heavy
    programs one device thread permanently misses the next cross-module
    rendezvous (7/8 arrive; terminate fires even at 1200 s on an idle
    core). The committed STABILITY_r04.json is the full 2,000-step run via
    ``tools/stability_segments.py`` (fresh process + checkpoint resume per
    500-step segment — which also exercises Adam/LoCo state carry across
    restarts)."""
    return _run_cpu_world8(STABILITY_SNIPPET, timeout=3000)


def offload_param_memory_evidence():
    """Compile-only ZeRO-Infinity evidence: with ``offload_param`` the
    stage-3 fp32 master moves from DEVICE arguments to HOST arguments in
    the compiled step (XLA memory_analysis) — the HBM residency drop the
    round-2 verdict asked to make driver-checkable."""
    import jax

    import deepspeed_tpu as dst
    from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

    out = {}
    for name, offp in (("baseline", None),
                       ("offload_param", {"device": "cpu"})):
        zero = {"stage": 3}
        if offp:
            zero["offload_param"] = offp
        config = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
                  "gradient_accumulation_steps": 1,
                  "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
                  "zero_optimization": zero, "bf16": {"enabled": True},
                  "steps_per_print": 10 ** 9,
                  "telemetry": _telemetry_section()}
        spec = dst.causal_lm_spec("gpt2_125m", remat="full",
                                  attention="flash")
        engine, *_ = dst.initialize(model=spec, config=config)
        fn = engine._build_train_step(1)
        batch = engine._shard_batch(engine._stack_micros(
            [next(synthetic_lm_data(8, 1024, 50257, seed=0))]), leading=True)
        with engine.mesh:
            ma = fn.lower(engine.state, batch).compile().memory_analysis()
        out[name] = {
            "device_arg_mb": round(ma.argument_size_in_bytes / 1e6),
            "host_arg_mb": round(ma.host_argument_size_in_bytes / 1e6),
            "temp_mb": round(ma.temp_size_in_bytes / 1e6)}
        del engine
        gc.collect()
    out["master_moved_to_host"] = \
        out["offload_param"]["host_arg_mb"] > 100
    # measured host<->device bandwidth THROUGH THIS RUNTIME — the number
    # that decides whether offload can also be a throughput path here. On a
    # real v5e host this link is PCIe (~16 GB/s) and ZeRO-Infinity-style
    # streaming overlaps with compute; through the remote-execution tunnel
    # it measures ~0.07 GB/s h2d / ~0.004 GB/s d2h (r5 probe), so offload
    # benches here are MEMORY evidence, not throughput claims.
    import numpy as np

    x = np.ones((64, 1024, 1024), np.float32)   # 256 MB
    t0 = time.perf_counter()
    d = jax.device_put(x)
    jax.block_until_ready(d)
    h2d = 0.25 / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    jax.device_get(d[:8])                       # 32 MB (d2h is ~20x slower)
    d2h = 0.03125 / (time.perf_counter() - t0)
    del d
    out["tunnel_h2d_gb_per_s"] = round(h2d, 3)
    out["tunnel_d2h_gb_per_s"] = round(d2h, 4)
    out["offload_note"] = (
        "host<->device through this runtime is a remote tunnel, not PCIe: "
        "offload rows are HBM-residency evidence; on-host deployments "
        "stream at PCIe rates (see docs/offload.md)")
    return out


def comm_bw_onchip():
    """On-chip collective bandwidth. At world=1 busbw is STRUCTURALLY zero
    ((n-1)/n factor) — emit a labeled skip instead of degenerate rows
    (round-4 verdict paper-cut a); on a pod this measures ICI."""
    import jax

    if jax.device_count() == 1:
        return {"skipped": "world=1 — busbw's (n-1)/n factor is 0 on a "
                           "single chip; comm_cpu_mesh_world8 carries the "
                           "non-degenerate collective evidence"}
    from deepspeed_tpu.utils.comm_bench import bench_collectives

    rows = bench_collectives(axis="data", sizes_mb=[64], trials=5)
    return [{"op": r["op"], "size_mb": round(r["size_bytes"] / 1e6),
             "algbw_gbps": round(r["algbw_gbps"], 2),
             "busbw_gbps": round(r["busbw_gbps"], 2)} for r in rows]


def comm_cpu_mesh_world8():
    """Both CPU-mesh comm lanes (collective busbw + compressed wire) in ONE
    subprocess — they share the world-8 mesh bring-up, and a second JAX
    import would double the entry's fixed cost for no signal."""
    snippet = CPU_SNIPPET_PRELUDE + r'''
import json
from deepspeed_tpu.comm.mesh import MeshConfig, initialize_mesh
from deepspeed_tpu.utils.comm_bench import bench_collectives, \
    bench_compressed_wire
mm = initialize_mesh(MeshConfig(data=8))
busbw = [{"op": r["op"], "size_mb": round(r["size_bytes"] / 1e6),
          "algbw_gbps": round(r["algbw_gbps"], 2),
          "busbw_gbps": round(r["busbw_gbps"], 2)}
         for r in bench_collectives(mesh=mm.mesh, axis="data",
                                    sizes_mb=[16], trials=3)]
wire = [{"op": r["op"],
         "wire_mb_per_rank": round(r["wire_bytes_per_rank"] / 1e6, 3),
         "wire_reduction": r["wire_reduction"],
         "rel_err": round(r["rel_err"], 5),
         "time_ms": round(r["time_s"] * 1e3, 1)}
        for r in bench_compressed_wire(mesh=mm.mesh, axis="data",
                                       size_mb=16, trials=3)]
print(json.dumps({"busbw_world8": busbw, "compressed_wire_world8": wire}))
'''
    return _run_cpu_world8(snippet)


ELASTIC_RESUME_SNIPPET = CPU_SNIPPET_PRELUDE + r'''
import json, os, tempfile, time
import numpy as np
import jax
import deepspeed_tpu as dst
from deepspeed_tpu.checkpoint.universal import convert_to_universal
from deepspeed_tpu.comm import mesh as mesh_mod

def spec():
    return dst.causal_lm_spec("tiny", dtype="float32", hidden_size=64,
                              num_layers=2, num_heads=4, max_seq_len=32)

def config():
    return {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3}, "steps_per_print": 10 ** 9}

rng = np.random.RandomState(0)
batch = {"tokens": rng.randint(0, 256, size=(8, 32)).astype(np.int32)}
it = iter(lambda: batch, None)
root = tempfile.mkdtemp(prefix="elastic_bench_")
ckpt = os.path.join(root, "ckpt")

e8, *_ = dst.initialize(model=spec(), config=config())
loss8 = 0.0
for _ in range(3):
    loss8 = float(e8.train_batch(it))
e8.save_checkpoint(ckpt)

t0 = time.perf_counter()
uni = convert_to_universal(ckpt, os.path.join(root, "universal"))
convert_s = time.perf_counter() - t0

# world 4 on the same 8-device host: explicit sub-mesh + mesh_manager
mesh_mod.reset_mesh()
mm = mesh_mod.initialize_mesh(mesh_mod.MeshConfig(data=4),
                              devices=jax.devices()[:4])
e4, *_ = dst.initialize(model=spec(), config=config(), mesh_manager=mm)
t0 = time.perf_counter()
e4.load_universal_checkpoint(uni)
reshard_s = time.perf_counter() - t0
loss4 = float(e4.train_batch(it))
print(json.dumps({
    "loss_world8": round(loss8, 6), "loss_world4_next": round(loss4, 6),
    "resumed_step": int(e4.global_steps),
    "convert_s": round(convert_s, 3), "reshard_s": round(reshard_s, 3),
    "elastic": {"from_world": 8, "to_world": 4,
                "convert_s": round(convert_s, 3),
                "reshard_s": round(reshard_s, 3)}}))
'''


def elastic_resume_bench():
    """World-elastic resume wall-time lane (README "Elastic worlds"):
    train zero-3 at the 8-virtual-device CPU world, convert the committed
    checkpoint to universal form (timed), rebuild at world 4 through an
    explicit sub-mesh, and reshard-load (timed). The ``elastic`` block is
    the schema-v2.4 record ``bench-diff`` tracks lower-is-better."""
    row = _run_cpu_world8(ELASTIC_RESUME_SNIPPET, timeout=280)
    if isinstance(row, list):
        return row[0] if row else {"error": "no output"}
    row["note"] = ("zero-3 checkpoint at world 8 resharded onto world 4 "
                   "(universal atoms through the commit protocol)")
    return row


def llama_3b_bench():
    """North-star-scale single-chip entry (round-4 verdict Missing #2): a
    ~3.3B-param llama-family model trained ON ONE CHIP's 16G HBM. The fit
    is TPU-native: Adafactor's factored second moment + bf16 params with
    stochastic rounding (no fp32 master) ≈ 8 bytes/param model+grad+state
    vs Adam's 14 fp32-master bytes (ops/optimizer.py Adafactor). Stage-3
    config for parity with the reference's north star (ZeRO-3 Llama,
    blogs/deepspeed-ulysses/README.md:83); at world=1 the stage-3 sharding
    is degenerate — the evidence here is model SCALE + MFU, the sharded
    path is exercised by the multichip dryrun and the CPU-mesh lanes.
    ZeRO-Infinity offload (the reference's route to this scale) is
    transfer-dead through this runtime — see offload_param_memory's
    measured tunnel bandwidth row."""
    return train_bench(
        "llama_3b", zero_stage=3, precision="bf16",
        optimizer="adafactor", optimizer_params={"lr": 1e-2},
        batch=4, seq_len=2048, gas=1, steps=4, windows=2, warms=2,
        config_extra={"bf16": {"enabled": True, "fp32_master": False},
                      "data_types": {"grad_accum_dtype": "bfloat16"}},
        note="3.1B params on one 16G chip: adafactor factored state + bf16 "
             "no-master (stochastic rounding) + bf16 grad buffer; stage-3 "
             "label is config parity — world=1 makes the sharding "
             "degenerate")


def qgz_llama_bench():
    """The quantized-wire measured row NEXT TO the exact llama row: the
    composed ZeRO++ pipeline (qgZ int8 gradient reduce-scatter + qwZ int8
    param gathers + LoCo error feedback, bucketed/chunked by the overlap
    scheduler) on the same llama-750m shape as ``zero3_llama_750m_bf16``.
    Its ``comms`` block carries the int8 wire bytes — ``bench-diff``
    prices the reduction lower-is-better against the exact row's.

    At world=1 the dp-manual axes are degenerate and the engine would
    silently fall back to exact collectives — a row LABELED qgz must not
    measure the exact wire, so it skips explicitly there (the CPU tier);
    on a mesh it measures. ``BENCH_WIRE=exact`` A/Bs this row too."""
    import jax

    if jax.device_count() < 2:
        return {"skipped": "qgZ wire needs dp world > 1 (a single chip "
                           "would silently measure exact collectives under "
                           "a qgz label); run on a mesh"}
    return train_bench(
        "llama_750m", zero_stage=2, precision="bf16",
        batch=4, seq_len=2048, gas=4, steps=4, windows=2,
        config_extra={"zero_optimization": {
            "stage": 2, "zero_quantized_weights": True,
            "zero_quantized_gradients": True, "loco_error_feedback": True}},
        note="composed quantized wire: qgZ+qwZ+LoCo under the bucketed "
             "overlap scheduler (ISSUE 10); diff comms.* against "
             "zero3_llama_750m_bf16 for the wire-byte reduction")


# (name, fn, cap_s, floor_s) in PRIORITY order: when the remaining global
# budget is below an entry's floor it is skipped with an explicit row. Caps
# are worst-case guards (hung compile, wedged tunnel), not expectations.
SUITE_SCHEDULE = [
    ("zero3_llama_3b_adafactor", llama_3b_bench, 540, 300),
    ("fastgen_paged_splitfuse_gpt2", fastgen_bench, 360, 150),
    ("fastgen_sla_poisson_gpt2", fastgen_sla_bench, 360, 150),
    ("fleet_sla_poisson_gpt2", fleet_sla_bench, 420, 150),
    ("fleet_sla_multitenant_gpt2", fleet_sla_multitenant_bench, 420, 150),
    ("moe_ulysses_moe_350m_bf16", lambda: train_bench(
        "moe_350m", zero_stage=2, precision="bf16",
        batch=16, seq_len=1024, gas=4, steps=8,
        attention="ulysses_flash", remat="selective",
        report_moe_drops=True,
        note="K=768 expert shapes are kernel-ceiling-bound (grouped GEMM "
             "~= dense matmul rate at this contraction; PROFILE.md r5 "
             "rungs) — moe_1b below shows the ratio flip at 2x hidden"),
        300, 120),
    ("moe_1b_large_experts", lambda: train_bench(
        "moe_1b", zero_stage=2, precision="bf16",
        optimizer="adafactor", optimizer_params={"lr": 1e-2},
        batch=16, seq_len=1024, gas=2, steps=4,
        attention="ulysses_flash", remat="full",
        config_extra={"bf16": {"enabled": True, "fp32_master": False},
                      "data_types": {"grad_accum_dtype": "bfloat16"}},
        windows=2, report_moe_drops=True,
        note="~2B-total/0.7B-active MoE on one chip: expert shapes where "
             "grouped GEMM matches dense throughput; fits via adafactor "
             "no-master + bf16 grad accumulation"), 300, 120),
    ("zero2_fusedadam_bert_large_fp16", lambda: train_bench(
        "bert_large", zero_stage=2, precision="fp16",
        optimizer="fusedadam", batch=16, seq_len=512, gas=4, steps=4,
        windows=2, spec_kwargs={"dtype": "bfloat16"},
        note="fp16 loss scaling/master + bf16 matmuls: the TPU MXU has no "
             "fp16 mode (f16 dots fail TPU compilation); bf16 is the "
             "hardware's 16-bit format"), 300, 120),
    ("zero3_llama_750m_bf16", lambda: train_bench(
        "llama_750m", zero_stage=3, precision="bf16",
        batch=4, seq_len=2048, gas=4, steps=4, windows=2), 300, 120),
    ("zero2_qgz_llama_750m_bf16", qgz_llama_bench, 300, 120),
    ("autotp_inference_gpt2_generate", inference_bench, 240, 90),
    ("offload_param_memory", offload_param_memory_evidence, 240, 100),
    ("autotune_smoke", autotune_smoke, 300, 120),
    ("autotune_plan", autotune_plan_roundtrip, 240, 60),
    ("comm_cpu_mesh_world8", comm_cpu_mesh_world8, 240, 90),
    ("elastic_resume", elastic_resume_bench, 300, 120),
    ("comm_bw_onchip", comm_bw_onchip, 120, 30),
]

def converge_real_text():
    """Real-data convergence lane (tools/converge_lane.py): held-out CE on
    real English text must DECREASE — the committed CONVERGE_r05.json is
    this lane's artifact (1000 steps, ~150 s on-chip)."""
    import subprocess

    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "converge_lane.py"),
         "/tmp/converge_lane.json"],
        capture_output=True, text=True, timeout=1200)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    return {"error": (out.stderr or "no output")[-300:]}


# long lanes: committed artifacts (STABILITY_r04.json, CONVERGE_r05.json)
# re-runnable under BENCH_LONG=1 — NOT part of the driver-budgeted default
# suite
LONG_SCHEDULE = [
    ("converge_real_text", converge_real_text, 1200, 300),
    ("stability_2k_cpu_mesh", stability_2k, 3300, 600),
    ("pipeline_1f1b_cpu_mesh", pipeline_bench, 2700, 600),
]

SUITE_ENTRIES = {name: fn for name, fn, _, _ in
                 SUITE_SCHEDULE + LONG_SCHEDULE}
SUITE_ENTRIES["headline"] = lambda: headline_entry()


def _entry_memory_stats() -> dict:
    """Peak host RSS for THIS entry — each suite entry is its own
    subprocess, so ``ru_maxrss`` is a clean per-row peak (Linux reports
    KB) — plus device allocator stats where the backend exposes them, so
    memory regressions are diffable next to speed ones (bench-diff treats
    ``memory.*`` as lower-is-better)."""
    out = {}
    try:
        import resource

        out["peak_host_rss_mb"] = round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    except (ImportError, ValueError, OSError):
        pass
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        keep = {k: int(v) for k, v in stats.items()
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "largest_alloc_size")}
        if keep:
            out["device"] = keep
    except (ImportError, IndexError, AttributeError, RuntimeError,
            TypeError, ValueError):
        pass   # CPU/older PJRT backends have no memory_stats
    return out


def _entry_guardian_stats() -> dict:
    """Training-guardian fault accounting for THIS entry (each entry is
    its own subprocess, so the process-wide counters are a clean per-row
    total). Embedded in every measured row so ``bench-diff`` can flag an
    anomaly-ridden round (``guardian.*`` diffs lower-is-better)."""
    try:
        from deepspeed_tpu import telemetry

        def total(name):
            counter = telemetry.get_registry().counter(name)
            return int(sum(v for _, v in counter.labels_items()))

        return {
            "skipped_steps": total("train_skipped_steps_total"),
            "anomalies": total("guardian_anomalies_total"),
            "rollbacks": total("guardian_rollbacks_total"),
            "quarantined_batches": total(
                "guardian_quarantined_batches_total"),
        }
    except Exception:
        return {}


def _entry_plan_stats() -> dict:
    """This entry's autotune plan-cache verdict (schema v2.3 ``plan``
    block). Each entry is its own subprocess, so the process-wide
    hit/miss counters ARE this row's engines: any hit → the row ran
    under a cached plan; any miss → it planned from scratch; neither →
    autotuning disabled (the default for most lanes)."""
    try:
        from deepspeed_tpu import telemetry

        def total(name):
            counter = telemetry.get_registry().counter(name)
            return int(sum(v for _, v in counter.labels_items()))

        if total("autotune_plan_cache_hits_total"):
            return {"status": "hit"}
        if total("autotune_plan_cache_misses_total"):
            return {"status": "miss"}
        return {"status": "disabled"}
    except Exception:
        return {}


def _run_entry_subprocess(name: str, timeout: float):
    """Run one suite entry in a child process so an XLA OOM/abort in a
    deliberately-HBM-tight config can't take the headline JSON down with it,
    and a hung one costs its own timeout, not the bench. The machinery
    (own session + group-kill, last-JSON-line contract) lives in
    ``deepspeed_tpu/bench/subproc.py`` — shared with the plan engine's
    measured-confirmation windows."""
    from deepspeed_tpu.bench.subproc import run_entry_subprocess

    return run_entry_subprocess(__file__, name, timeout)


def _logs_to_stderr():
    """The driver contract is ONE JSON line on stdout; the framework logger
    streams INFO to stdout (reference behavior) — rehome it for the bench."""
    import logging

    import deepspeed_tpu.utils.logging  # noqa: F401 — creates the handler

    for h in logging.getLogger("deepspeed_tpu").handlers:
        if getattr(h, "stream", None) is sys.stdout:
            h.setStream(sys.stderr)


def headline_entry():
    """Headline train bench + measured ceiling, as one subprocess entry —
    the orchestrator merges the returned dict into the top-level JSON."""
    import jax

    n_chips = jax.device_count()
    batch_per_chip = int(os.environ.get("BENCH_BATCH", 32))
    seq_len = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 6))
    gas = int(os.environ.get("BENCH_GAS", 4))
    model = os.environ.get("BENCH_MODEL", "gpt2_125m")
    attention = os.environ.get("BENCH_ATTENTION",
                               "flash" if model != "tiny" else "xla")
    remat = os.environ.get("BENCH_REMAT", "full")
    loss_tiles = int(os.environ.get("BENCH_LOSS_TILES", 0))
    # measured SLOWER on v5e at 125M (the per-layer concat inside the scan
    # re-materializes 2304x768 bf16 per layer per step — bandwidth beats the
    # one-matmul win); keep opt-in for big-hidden models where the ratio flips
    fuse_qkv = os.environ.get("BENCH_FUSE_QKV", "0") != "0"

    headline = train_bench(
        model, zero_stage=1, precision="bf16", batch=batch_per_chip,
        seq_len=seq_len, gas=gas, steps=steps, attention=attention,
        remat=remat, spec_kwargs={"loss_tiles": loss_tiles,
                                  "fuse_qkv": fuse_qkv})

    # Baseline: the reference's own best published sustained training rate —
    # ">175 TFlops/GPU (>54% of HW peak)" on A100s, DeepSpeed-Ulysses blog
    # (reference blogs/deepspeed-ulysses/README.md:83; BASELINE.md #4).
    # Converted to tokens/s for THIS bench's model via the same model-FLOPs
    # formula the MFU uses. Conservative referent: that number is the
    # reference's large-dense-model best case — a 125M model with its big
    # vocab-head fraction would not hit 54% MFU on an A100 either.
    # MEASURED matmul ceiling through this runtime (vs_ceiling's referent —
    # driver-verifiable, not a prose claim). ONE rung at the default iters:
    # the r4 4-rung shape-matched ladder lives in PROFILE.md as a committed
    # artifact; re-measuring it every run was part of why r4 timed out.
    ceiling = None
    if os.environ.get("BENCH_CEILING", "1") != "0":
        try:
            ceiling = round(measure_matmul_ceiling(), 1)
        except Exception:
            ceiling = None
    # same-model-FLOPs conversion: baseline tokens/s = 175 TFLOP/s ÷ this
    # model's FLOPs/token (ratio == achieved TFLOP/s ÷ 175). Degenerate on
    # tiny smoke models whose TFLOP/s rounds to 0 — emit null there.
    tfl = headline["model_tflops_per_sec_chip"]
    baseline_tps = (BASELINE_TFLOPS_CITED * headline["tokens_per_sec_chip"]
                    / tfl) if tfl >= 0.1 else None
    win = headline.get("window_samples_tokens_per_sec") or []
    dev = jax.devices()[0]
    return {
        "metric": f"tokens/sec/chip {model} zero1 bf16",
        "value": headline["tokens_per_sec_chip"],
        "unit": "tokens/s/chip",
        # platform/device identity: the gate refuses to baseline a TPU
        # round against a CPU what-if run (and vice versa)
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        # the run-to-run tunnel variance as a FIRST-CLASS band (round-4
        # verdict paper-cut b): value is the best window, the band is what
        # repeated runs should reproduce
        "value_band": [min(win), max(win)] if win else None,
        "vs_baseline": round(headline["model_tflops_per_sec_chip"]
                             / BASELINE_TFLOPS_CITED, 3),
        "baseline_tokens_per_sec": (round(baseline_tps, 1)
                                    if baseline_tps else None),
        "baseline_citation": "175 TFLOP/s/GPU sustained (>54% A100 peak), "
                             "DeepSpeed-Ulysses — reference "
                             "blogs/deepspeed-ulysses/README.md:83 "
                             "(BASELINE.md #4); converted at this model's "
                             "FLOPs/token",
        "model_tflops_per_sec_chip": headline["model_tflops_per_sec_chip"],
        "mfu": headline["mfu"],
        "peak_tflops": chip_peak_tflops(jax.devices()[0]),
        "matmul_ceiling_tflops": ceiling,
        "vs_ceiling": (round(headline["model_tflops_per_sec_chip"] / ceiling,
                             3) if ceiling else None),
        # chip-executed FLOPs (incl. remat=full's backward recompute of the
        # scanned body) against the same measured ceiling — the utilization
        # number the remat policy can actually influence; the r4 sweep
        # (PROFILE.md) shows trading the recompute for saved activations is
        # memory-bound on v5e and loses throughput
        "hardware_tflops_per_sec_chip":
            headline["hardware_tflops_per_sec_chip"],
        "vs_ceiling_hardware":
            (round(headline["hardware_tflops_per_sec_chip"] / ceiling, 3)
             if ceiling else None),
        "window_samples_tokens_per_sec": win,
        "loss": headline.get("loss"),
        "n_chips": n_chips,
        # v2.1: ledger totals + overlap ride in the headline block too —
        # the round-over-round wire-byte diff reads them from here
        **({"comms": headline["comms"]} if "comms" in headline else {}),
        **({"overlap_fraction": headline["overlap_fraction"]}
           if "overlap_fraction" in headline else {}),
    }


def _hlolint_entry_gate(engine, seq_len):
    """Refuse to record a train row whose LOWERED step violates its
    compiled-program contract (``deepspeed_tpu/analysis/hlolint``): the
    structural rules always run against the engine's resolved config
    (wire format, overlap plan, bucket plan), and
    ``BENCH_HLOLINT_CONTRACT`` names a committed contract JSON to hold
    the step to on top. A violating round's numbers are
    unrepresentative by construction — the "optimization" being
    measured isn't in the program. ``BENCH_HLOLINT=0`` opts out for
    local what-if runs, mirroring ``BENCH_DSLINT``; a broken linter
    degrades to ungated, never kills the measured row."""
    if os.environ.get("BENCH_HLOLINT", "1") == "0":
        return
    contract = os.environ.get("BENCH_HLOLINT_CONTRACT") or None
    try:
        findings = engine.lint_step(contract=contract, seq_len=seq_len)
    except Exception as e:
        if contract and type(e).__name__ == "ContractError":
            # the operator EXPLICITLY named a contract: a typo'd path or
            # malformed file must fail the row, not silently disarm the
            # gate the operator believes is armed
            raise RuntimeError(
                f"hlolint: cannot enforce BENCH_HLOLINT_CONTRACT="
                f"{contract}: {e}") from e
        print(f"bench: hlolint gate unavailable ({type(e).__name__}: {e});"
              " proceeding ungated", file=sys.stderr)
        return
    if findings:
        for f in findings[:20]:
            print(f"bench: hlolint: {f.render()}", file=sys.stderr)
        raise RuntimeError(
            f"hlolint: {len(findings)} compiled-program contract "
            f"violation(s) in the lowered step — refusing to record "
            f"(first: {findings[0].render()[:160]}; BENCH_HLOLINT=0 "
            "overrides locally)")


def _memlint_entry_gate(engine, seq_len):
    """Refuse to record a train row whose LOWERED step violates its
    MEMORY contract (``deepspeed_tpu/analysis/memlint`` — hlolint's
    memory-side sibling): donation/aliasing verification, residency vs
    the ZeRO prediction, and ``BENCH_MEMLINT_CONTRACT`` naming a
    committed memory contract to hold the step to. ``BENCH_MEMLINT=0``
    opts out for local what-if runs; an EXPLICITLY-set-but-unreadable
    contract fails the row (the gate the operator believes is armed
    must not silently disarm), while internal linter breakage degrades
    to ungated."""
    if os.environ.get("BENCH_MEMLINT", "1") == "0":
        return
    contract = os.environ.get("BENCH_MEMLINT_CONTRACT") or None
    try:
        findings = engine.lint_memory(contract=contract, seq_len=seq_len)
    except Exception as e:
        if contract and type(e).__name__ == "ContractError":
            raise RuntimeError(
                f"memlint: cannot enforce BENCH_MEMLINT_CONTRACT="
                f"{contract}: {e}") from e
        print(f"bench: memlint gate unavailable ({type(e).__name__}: {e});"
              " proceeding ungated", file=sys.stderr)
        return
    if findings:
        for f in findings[:20]:
            print(f"bench: memlint: {f.render()}", file=sys.stderr)
        raise RuntimeError(
            f"memlint: {len(findings)} memory contract violation(s) in "
            f"the lowered step — refusing to record "
            f"(first: {findings[0].render()[:160]}; BENCH_MEMLINT=0 "
            "overrides locally)")


def _dslint_gate():
    """Refuse to record benchmarks from a tree carrying new (non-baselined)
    dslint findings: a host-sync or lock hazard that slipped in makes the
    numbers unrepresentative at best and racy at worst, and a recorded
    BENCH_*.json outlives the bug. Returns the new findings (None = clean
    or gate unavailable). ``BENCH_DSLINT=0`` opts out for local what-if
    runs — the committed history stays gated."""
    if os.environ.get("BENCH_DSLINT", "1") == "0":
        return None
    try:
        from deepspeed_tpu import analysis

        new, _ = analysis.lint_repo()
    except Exception as e:   # a broken linter must not kill benchmarking
        print(f"bench: dslint gate unavailable ({type(e).__name__}: {e}); "
              "proceeding ungated", file=sys.stderr)
        return None
    return new or None


def _racelint_gate():
    """Refuse to record benchmarks from a racelint-dirty tree (mirrors
    ``BENCH_DSLINT``): an unguarded thread-shared write or a lock-order
    cycle in the control plane makes every number suspect — the scrape
    thread, watchdog, or async finalizer may be perturbing (or
    corrupting) the very counters being recorded. ``BENCH_RACELINT=0``
    opts out for local what-if runs; the committed history stays gated."""
    if os.environ.get("BENCH_RACELINT", "1") == "0":
        return None
    try:
        from deepspeed_tpu.analysis import racelint

        new, _ = racelint.lint_repo()
    except Exception as e:   # a broken linter must not kill benchmarking
        print(f"bench: racelint gate unavailable ({type(e).__name__}: "
              f"{e}); proceeding ungated", file=sys.stderr)
        return None
    return new or None


def main():
    _logs_to_stderr()
    if len(sys.argv) >= 3 and sys.argv[1] == "--entry":
        name = sys.argv[2]
        try:
            # arm the structured tracer for the whole entry (BENCH_TRACING=0
            # opts out): the row then carries per-phase latency
            # DISTRIBUTIONS, not just the snapshot's means
            try:
                from deepspeed_tpu.telemetry import tracing as _tracing

                _tracing.configure(
                    enabled=os.environ.get("BENCH_TRACING", "1") != "0",
                    capacity=8192)
            except Exception:
                pass
            row = SUITE_ENTRIES[name]()
            if isinstance(row, dict) and "error" not in row:
                # each bench row carries its telemetry context (metric name
                # catalog: README "Observability") — MFU/latency numbers in
                # BENCH_*.json are re-derivable from this snapshot
                try:
                    from deepspeed_tpu import telemetry

                    snap = telemetry.snapshot()
                    if any(snap.values()):
                        row["telemetry"] = snap
                    # per-phase p50/p95/p99 span durations from the trace
                    # buffer: the latency-distribution companion to the
                    # snapshot's aggregate means
                    phases = telemetry.get_tracer().phase_stats()
                    if phases:
                        row["trace_phases"] = phases
                except Exception:
                    pass
                mem = _entry_memory_stats()
                if mem:
                    # merge, don't replace: the entry body may already
                    # carry compiled-program memory_analysis legs
                    merged = dict(row.get("memory") or {})
                    merged.update(mem)
                    row["memory"] = merged
                guardian = _entry_guardian_stats()
                if guardian:
                    row["guardian"] = guardian
                plan_stats = _entry_plan_stats()
                if plan_stats:
                    row["plan"] = plan_stats
            print(json.dumps(row))
        except Exception as e:
            print(json.dumps({"error": f"{type(e).__name__}: {e}"[:200]}))
        return 0

    # ---- budget-orchestrated run: every entry is a bounded subprocess ----
    # domino overlap flags (runtime/domino.py): probe-gated against this
    # jaxlib, applied to the environment every entry SUBPROCESS inherits
    # (the parent never builds a backend, so the children get them before
    # their first jax use). On builds without the flags — e.g. the CPU
    # tier — they're logged and skipped, never a hard abort.
    if os.environ.get("BENCH_OVERLAP_FLAGS", "1") != "0":
        try:
            from deepspeed_tpu.runtime.domino import apply_overlap_flags

            applied = apply_overlap_flags()
            if applied:
                print(f"bench: overlap XLA flags armed: {applied}",
                      file=sys.stderr)
        except Exception as e:   # flags are an optimization, never a gate
            print(f"bench: overlap-flag probe unavailable "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
    findings = _dslint_gate()
    if findings:
        for f in findings[:20]:
            print(f"bench: {f.render()}", file=sys.stderr)
        print(json.dumps({
            "metric": "bench refused: dslint found new hazards",
            "value": 0, "unit": "findings",
            "error": f"dslint: {len(findings)} new non-baselined "
                     "finding(s) — fix or baseline them before recording "
                     "benchmarks (BENCH_DSLINT=0 overrides locally)"}))
        return 1
    findings = _racelint_gate()
    if findings:
        for f in findings[:20]:
            print(f"bench: {f.render()}", file=sys.stderr)
        print(json.dumps({
            "metric": "bench refused: racelint found new hazards",
            "value": 0, "unit": "findings",
            "error": f"racelint: {len(findings)} new non-baselined "
                     "concurrency finding(s) — fix or suppress them "
                     "before recording benchmarks (BENCH_RACELINT=0 "
                     "overrides locally)"}))
        return 1

    elapsed = {}

    def run_timed(name, cap, floor):
        rem = _remaining_budget()
        if rem < floor:
            return {"skipped": f"budget ({int(rem)}s left < {floor}s floor)"}
        t0 = time.monotonic()
        row = _run_entry_subprocess(name, timeout=min(cap, rem))
        elapsed[name] = round(time.monotonic() - t0, 1)
        if rem < cap and isinstance(row, dict) \
                and str(row.get("error", "")).startswith("entry timed out"):
            # timed out at a BUDGET-clamped cap (not its nominal one):
            # that's starvation, not breakage — it must diff as a budget
            # skip, not a measured->error gate regression
            return {"skipped": f"budget (timed out at clamped {int(rem)}s"
                               f" < {cap}s cap)"}
        return row

    # the observatory is auxiliary like every other bench subsystem: a
    # broken deepspeed_tpu/bench package must degrade to an ungated
    # legacy line, not kill the run AFTER the chip time was spent (the
    # r04 husk failure mode this package exists to close)
    try:
        from deepspeed_tpu.bench import gate as bench_gate
        from deepspeed_tpu.bench import history as bench_history
        from deepspeed_tpu.bench import schema as bench_schema
    except Exception as e:
        print(f"bench: observatory unavailable ({type(e).__name__}: {e});"
              " emitting ungated legacy line", file=sys.stderr)
        bench_gate = bench_history = bench_schema = None

    # headline first — it owns the metric line; a failure degrades to an
    # error row with value 0 (the driver contract needs the line either way)
    head = run_timed("headline", cap=600, floor=120)
    if "value" not in head:
        _m = os.environ.get("BENCH_MODEL", "gpt2_125m")
        head = {"metric": f"tokens/sec/chip {_m} zero1 bf16",
                "value": 0, "unit": "tokens/s/chip", "vs_baseline": 0,
                "error": head.get("error", head.get("skipped", "unknown"))}
    headline = dict(head)
    if "headline" in elapsed:
        headline["elapsed_s"] = elapsed["headline"]

    rows = {}
    if os.environ.get("BENCH_SUITE", "1") != "0":
        schedule = list(SUITE_SCHEDULE)
        if os.environ.get("BENCH_LONG", "0") != "0":
            schedule += LONG_SCHEDULE
        for name, _, cap, floor in schedule:
            rows[name] = run_timed(name, cap, floor)

    if bench_schema is None:
        result = dict(head)
        if rows:
            result["configs"] = rows
        result["budget_s"] = BENCH_BUDGET_S
        result["total_runtime_s"] = round(time.monotonic() - BENCH_T0, 1)
        result["entry_elapsed_s"] = elapsed
        print(json.dumps(result))
        return 0

    # schema v2 (deepspeed_tpu/bench/schema.py): driver-contract keys stay
    # top-level, everything else lives in the structured headline block +
    # normalized entries — and the result is VALIDATED before it prints,
    # so "parsed": null (r03–r05) can't silently happen again
    result = {
        "schema_version": bench_schema.SCHEMA_VERSION,
        "metric": headline["metric"],
        "value": headline["value"],
        "unit": headline["unit"],
        "vs_baseline": headline.get("vs_baseline", 0),
        "headline": headline,
    }
    entries = {
        name: bench_schema.normalize_entry_row(row, elapsed.get(name))
        for name, row in rows.items()}
    result["entries"] = entries

    # surface the best-utilization training row in the headline block: the
    # 125M headline keeps cross-round comparability, but its small-shape
    # MFU is architecture-bound (PROFILE.md ceiling ladder) — the
    # framework's utilization story is the north-star-scale rows below it
    best = {"name": "headline", "mfu": headline.get("mfu") or 0,
            "model_tflops_per_sec_chip":
                headline.get("model_tflops_per_sec_chip")}
    for name, entry in entries.items():
        metrics = entry.get("metrics") or {}
        if (metrics.get("mfu") or 0) > best["mfu"]:
            best = {"name": name, "mfu": metrics["mfu"],
                    "model_tflops_per_sec_chip":
                        metrics.get("model_tflops_per_sec_chip")}
    if best.get("model_tflops_per_sec_chip"):
        best["vs_baseline"] = round(
            best["model_tflops_per_sec_chip"] / BASELINE_TFLOPS_CITED, 3)
    headline["best_row"] = best

    result["budget_s"] = BENCH_BUDGET_S
    result["total_runtime_s"] = round(time.monotonic() - BENCH_T0, 1)

    # same refusal posture as the dslint gate: a result that fails its own
    # schema is not recordable evidence — print an explicit refusal line
    # (the driver contract still gets ONE JSON line) and exit nonzero
    errors = bench_schema.validate_result(result)
    if errors:
        for err in errors[:20]:
            print(f"bench: schema: {err}", file=sys.stderr)
        print(json.dumps({
            "metric": "bench refused: result failed schema validation",
            "value": 0, "unit": "schema errors",
            "error": f"schema v{bench_schema.SCHEMA_VERSION}: "
                     f"{len(errors)} validation error(s) — first: "
                     f"{errors[0][:160]}"}))
        return 1

    # regression gate (deepspeed_tpu/bench/gate.py): fresh result vs the
    # latest bench_history record; >threshold headline/per-entry drops fail
    # the run (exit 1) with per-phase attribution on stderr. A broken gate
    # must not kill benchmarking — GATE_ERROR degrades to ungated.
    gate_rc, gate_info = bench_gate.run_gate(result)
    result["gate"] = gate_info

    print(json.dumps(result))

    if os.environ.get("BENCH_RECORD", "1") != "0":
        try:
            # record rc = did THIS run pass (baseline-worthiness): only a
            # real regression disqualifies it; a gate-internal error does
            # not taint an otherwise valid round
            bench_history.append_record(bench_history.record_from_result(
                result,
                rc=1 if gate_rc == bench_gate.GATE_REGRESSED else 0))
        except OSError as e:
            print(f"bench: history append failed: {e}", file=sys.stderr)
    if gate_rc == bench_gate.GATE_REGRESSED:
        for reg in gate_info.get("regressions", [])[:10]:
            print(f"bench: GATE: {reg.get('where')}.{reg.get('metric')} "
                  f"{reg.get('old')} -> {reg.get('new')} "
                  f"({reg.get('delta_frac')})", file=sys.stderr)
        for line in gate_info.get("attribution", [])[:5]:
            print(f"bench: GATE: {line}", file=sys.stderr)
        print(f"bench: GATE: regression vs {gate_info.get('baseline')} "
              f"past {gate_info.get('threshold')} — exit 1 "
              "(BENCH_GATE=0 or BENCH_GATE_THRESHOLD= override)",
              file=sys.stderr)
        return 1
    if gate_rc == bench_gate.GATE_ERROR:
        print(f"bench: gate unavailable ({gate_info.get('error')}); "
              "proceeding ungated", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
