#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Metric: tokens/sec/chip for GPT-2-125M causal-LM training (ZeRO-1, bf16,
fused jitted train step) on the available device(s). ``vs_baseline`` compares
against an estimated NCCL/A100 DeepSpeed throughput for the same model
(A100 bf16 peak 312 TFLOPs at ~40% MFU → ~167k tokens/s for a 125M-param model;
see BASELINE.md — the reference publishes no directly comparable table).
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "")


def main():
    import jax
    import numpy as np

    import deepspeed_tpu as dst
    from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

    n_chips = jax.device_count()
    batch_per_chip = int(os.environ.get("BENCH_BATCH", 8))
    seq_len = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    model = os.environ.get("BENCH_MODEL", "gpt2_125m")

    spec = dst.causal_lm_spec(model, remat="none")
    config = {
        "train_batch_size": batch_per_chip * n_chips,
        "train_micro_batch_size_per_gpu": batch_per_chip,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }
    engine, *_ = dst.initialize(model=spec, config=config)
    data = synthetic_lm_data(batch_per_chip * n_chips, seq_len,
                             spec_vocab(spec), seed=0)

    # warmup (compile)
    for _ in range(3):
        loss = engine.train_batch(data)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(data)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    tokens = steps * batch_per_chip * n_chips * seq_len
    tokens_per_sec_chip = tokens / dt / n_chips
    baseline = 167_000.0  # est. A100 DeepSpeed tokens/s/GPU for 125M @ 40% MFU
    print(json.dumps({
        "metric": "tokens/sec/chip gpt2_125m zero1 bf16",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec_chip / baseline, 3),
    }))


def spec_vocab(spec):
    from deepspeed_tpu.models.transformer import PRESETS

    return PRESETS[os.environ.get("BENCH_MODEL", "gpt2_125m")].vocab_size


if __name__ == "__main__":
    sys.exit(main())
