#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line for the driver.

Metric: tokens/sec/chip for GPT-2-125M causal-LM training (ZeRO-1, bf16,
fused jitted train step) on the available device(s). ``vs_baseline`` compares
against an estimated NCCL/A100 DeepSpeed throughput for the same model
(A100 bf16 peak 312 TFLOPs at ~40% MFU → ~167k tokens/s for a 125M-param model;
see BASELINE.md — the reference publishes no directly comparable table).
The line also reports achieved model TFLOP/s and MFU against the chip's bf16
peak so progress is self-evident independent of the baseline estimate.

Tuned config (measured on v5e, see PROFILE.md): micro-batch 32, remat=full,
Pallas flash attention with 512/1024 blocks, bf16 head matmul with fp32
accumulation. BENCH_* env vars override for ablations.
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "")

# bf16 peak TFLOP/s per chip, by TPU generation (fallback: v5e)
PEAK_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0,
               "v6e": 918.0, "v6 lite": 918.0}


def chip_peak_tflops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in PEAK_TFLOPS.items():
        if key in kind:
            return peak
    return 197.0


def main():
    import jax

    import deepspeed_tpu as dst
    from deepspeed_tpu.models.transformer import PRESETS
    from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

    n_chips = jax.device_count()
    batch_per_chip = int(os.environ.get("BENCH_BATCH", 32))
    seq_len = int(os.environ.get("BENCH_SEQ", 1024))
    steps = int(os.environ.get("BENCH_STEPS", 6))
    gas = int(os.environ.get("BENCH_GAS", 4))
    model = os.environ.get("BENCH_MODEL", "gpt2_125m")

    # flash attention (no [S,S] score materialization — fits 16G HBM at
    # batch 32 x 1024) + per-layer remat; gas micro-batches scanned INSIDE one
    # jitted step so per-dispatch overhead amortizes over gas x batch x seq
    # tokens.
    attention = os.environ.get("BENCH_ATTENTION",
                               "flash" if model != "tiny" else "xla")
    remat = os.environ.get("BENCH_REMAT", "full")
    loss_tiles = int(os.environ.get("BENCH_LOSS_TILES", 0))
    spec = dst.causal_lm_spec(model, remat=remat,
                              attention=attention, loss_tiles=loss_tiles)
    config = {
        "train_batch_size": batch_per_chip * gas * n_chips,
        "train_micro_batch_size_per_gpu": batch_per_chip,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }
    engine, *_ = dst.initialize(model=spec, config=config)
    cfg = PRESETS[model]
    data = synthetic_lm_data(batch_per_chip * n_chips, seq_len,
                             cfg.vocab_size, seed=0)

    # warmup (compile); float() forces a real host sync (block_until_ready
    # may return early through remote-execution tunnels)
    for _ in range(2):
        loss = engine.train_batch(data)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(data)
    float(loss)
    dt = time.perf_counter() - t0

    tokens = steps * gas * batch_per_chip * n_chips * seq_len
    tokens_per_sec_chip = tokens / dt / n_chips
    # model FLOPs: 6*N per token (fwd+bwd matmuls) + causal attention
    # 12*L*H*S*0.5; remat recompute is NOT counted (model FLOPs, not hardware)
    n_params = spec.num_params or 0
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq_len
    achieved_tflops = flops_per_token * tokens_per_sec_chip / 1e12
    peak = chip_peak_tflops(jax.devices()[0])
    baseline = 167_000.0  # est. A100 DeepSpeed tokens/s/GPU for 125M @ 40% MFU
    print(json.dumps({
        "metric": f"tokens/sec/chip {model} zero1 bf16",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec_chip / baseline, 3),
        "model_tflops_per_sec_chip": round(achieved_tflops, 1),
        "mfu": round(achieved_tflops / peak, 3),
        "peak_tflops": peak,
    }))


if __name__ == "__main__":
    sys.exit(main())
