#!/usr/bin/env python
"""2,000-step stability artifact runner (STABILITY_r05.json).

Runs each exotic-engine lane for 2,000 optimizer steps in FOUR 500-step
SEGMENTS, each in a fresh subprocess resuming from the previous segment's
checkpoint. Segmentation is a deliberate workaround for an XLA:CPU runtime
defect observed on the 8-virtual-device single-core mesh: after ~1,000
executions of collective-heavy programs (the qgZ per-leaf quantized
all-gathers), one device thread permanently fails to join the next
cross-module rendezvous — 7 of 8 arrive, and the terminate deadline fires
even at 1,200 s on an idle core (rendezvous.cc:127). Fresh processes reset
the runtime well below that horizon; the checkpoint/resume between segments
additionally exercises persistent-state carry (Adam moments, LoCo error
residuals, curriculum step) across restarts — the reference's
nightly-convergence-suite concern (SURVEY §4).

Usage: python tools/stability_segments.py  (writes STABILITY_r05.json)
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SEGMENT = r'''
import itertools, json, sys
import jax
jax.config.update("jax_platforms", "cpu")
import deepspeed_tpu as dst
from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.runtime.dataloader import synthetic_lm_data

zero_cfg = json.loads(sys.argv[1])
ckpt_dir = sys.argv[2]
steps, window = int(sys.argv[3]), 100

mesh_mod.reset_mesh()
spec = dst.causal_lm_spec("tiny", dtype="float32", num_layers=2,
                          max_seq_len=64)
config = {"train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
          "gradient_accumulation_steps": 1,
          "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
          "zero_optimization": zero_cfg, "steps_per_print": 10 ** 9}
engine, *_ = dst.initialize(model=spec, config=config)
import os
if os.path.exists(os.path.join(ckpt_dir, "latest")):
    engine.load_checkpoint(ckpt_dir)
corpus = [b for b, _ in zip(synthetic_lm_data(8, 64, 512, seed=0),
                            range(16))]
losses = []
for _ in range(steps // window):
    loss = engine.train_batches(itertools.cycle(corpus), window)
    losses.append(round(float(loss), 4))
engine.save_checkpoint(ckpt_dir)
print("SEGMENT_RESULT " + json.dumps(
    {"losses": losses, "step": int(engine.global_steps)}))
'''

RUNS = {
    "zero3_offload_param": {"stage": 3, "offload_param": {"device": "cpu"}},
    "zero2_qgz_loco": {"stage": 2, "zero_quantized_gradients": True,
                       "loco_error_feedback": True},
    "exact_zero2": {"stage": 2},
}


def main(total_steps=2000, seg_steps=500, only=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", DSTPU_ACCELERATOR="cpu",
               PYTHONPATH=REPO,
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"
                          + " --xla_cpu_collective_call_warn_stuck_timeout_"
                            "seconds=300"
                          + " --xla_cpu_collective_call_terminate_timeout_"
                            "seconds=1200"))
    prior_path = os.path.join(REPO, "STABILITY_r05.json")
    out = {}
    if only and os.path.exists(prior_path):
        with open(prior_path) as f:
            out = {k: v for k, v in json.load(f).items()
                   if k in RUNS and isinstance(v, dict) and "error" not in v}
    for name, zc in RUNS.items():
        if only and name != only or name in out:
            continue
        ckpt = tempfile.mkdtemp(prefix=f"stab_{name}_")
        losses = []
        for seg in range(total_steps // seg_steps):
            # the XLA:CPU thread-loss is flaky and can strike any segment:
            # a crashed attempt left no checkpoint for its steps, so a
            # retry simply resumes from the last good segment boundary
            for attempt in range(3):
                p = subprocess.run(
                    [sys.executable, "-c", SEGMENT, json.dumps(zc), ckpt,
                     str(seg_steps)],
                    capture_output=True, text=True, env=env, timeout=3000)
                line = [ln for ln in p.stdout.splitlines()
                        if ln.startswith("SEGMENT_RESULT ")]
                if p.returncode == 0 and line:
                    break
                print(f"{name} segment {seg} attempt {attempt} failed rc="
                      f"{p.returncode}", flush=True)
            else:
                out[name] = {"error": (p.stderr or "no output")[-400:],
                             "failed_segment": seg}
                break
            res = json.loads(line[-1].split(" ", 1)[1])
            losses.extend(res["losses"])
            print(f"{name} segment {seg}: step {res['step']} "
                  f"loss {res['losses'][-1]}", flush=True)
        else:
            out[name] = {"first": losses[0], "last": losses[-1],
                         "min": min(losses), "max": max(losses),
                         "finite": all(x == x and abs(x) < 1e30
                                       for x in losses),
                         "monotone_trend": losses[-1] < losses[0] - 1.0,
                         "curve_every_100": losses}
    if all("error" not in v for v in out.values()) and len(out) == len(RUNS):
        ex = out["exact_zero2"]["last"]
        out["final_loss_max_abs_dev_vs_exact"] = round(max(
            abs(out["zero3_offload_param"]["last"] - ex),
            abs(out["zero2_qgz_loco"]["last"] - ex)), 4)
    out["steps"] = total_steps
    out["method"] = ("4x500-step segments, fresh process + checkpoint "
                     "resume per segment (XLA:CPU rendezvous thread-loss "
                     "workaround past ~1k collective-heavy executions; "
                     "resume also exercises Adam/LoCo state carry)")
    with open(os.path.join(REPO, "STABILITY_r05.json"), "w") as f:
        json.dump(out, f, indent=1)
    print("WROTE STABILITY_r05.json")


if __name__ == "__main__":
    main(only=sys.argv[1] if len(sys.argv) > 1 else None)
