#!/usr/bin/env python
"""Reproducibly regenerate the committed ``observatory_fixtures/*.hlo.txt``.

Every committed HLO fixture (and therefore every committed hlolint
contract in ``deepspeed_tpu/analysis/hlolint/contracts/``) was generated
from a PINNED engine config under ``JAX_PLATFORMS=cpu`` with 8 forced
host devices. This tool is that generation path as a committed,
re-runnable artifact: fixtures and contracts can be rebuilt TOGETHER
after an intentional program change (new jax pin, scheduler rework)
instead of by hand — and reviewed together, since loosening a committed
contract is refused unless ``--allow-loosen`` is passed through.

Each fixture is generated in its own subprocess (fresh backend, the
pinned env) via this file's ``--_generate`` child mode:

* build the pinned engine config;
* lower the REAL fused train step through the observatory's
  ``ledger_for_engine`` (the same mirrored builder selection the hot
  path and ``engine.lint_step`` use);
* trim to the module header + every collective-bearing line
  (``hlo.iter_collective_lines`` — full dumps are ~1 MB, the ledger
  parser is line-oriented);
* for the ``*_async_*`` fixtures, pass the trimmed lines through
  ``hlo.asyncify_hlo`` (the surface transform XLA's
  async-collective-creator pass applies on TPU/GPU; CPU lowers
  sync-only).

Usage::

    tools/regen_hlo_fixtures.py --list                 # what would run
    tools/regen_hlo_fixtures.py --out /tmp/fx          # all fixtures, elsewhere
    tools/regen_hlo_fixtures.py --only zero2_tiny_step # one fixture
    tools/regen_hlo_fixtures.py --write-contracts      # + retighten contracts
    tools/regen_hlo_fixtures.py --write-contracts --allow-loosen  # regeneration
"""
import argparse
import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

FIXTURES_DIR = os.path.join(REPO_ROOT, "tests", "unit",
                            "observatory_fixtures")

#: the pinned generation env every fixture was produced under
PINNED_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
    # conftest flips this for the test processes that consume the
    # fixtures; generation must match or param-init PRNGs diverge
    "JAX_THREEFRY_PARTITIONABLE": "true",
}

_FORCING = {"overlap_comm": True, "reduce_bucket_size": 4096,
            "allgather_bucket_size": 8192,
            "stage3_prefetch_bucket_size": 8192}

#: the pinned per-fixture configs. ``spec``/``engine`` feed
#: deepspeed_tpu.initialize; ``seq_len`` is the lowered batch shape;
#: ``asyncify`` applies hlo.asyncify_hlo to the trimmed lines.
FIXTURE_SPECS = {
    "zero2_tiny_step": {
        "spec": dict(model="tiny", num_layers=2, max_seq_len=64),
        "zero": {"stage": 2, "overlap_comm": False},
        "banner": "the REAL zero2 tiny-model train step (PR 7 ledger "
                  "fixture; unbucketed — overlap_comm off)",
    },
    "zero3_tiny_step": {
        "spec": dict(model="tiny", num_layers=2, max_seq_len=64),
        "zero": {"stage": 3, "overlap_comm": False},
        "banner": "the REAL zero3 tiny-model train step (PR 7 ledger "
                  "fixture; unbucketed — overlap_comm off)",
    },
    "moe_tiny_step": {
        "spec": dict(model="tiny_moe", max_seq_len=64),
        "zero": {"stage": 2, "overlap_comm": False},
        "mesh": {"data": 2, "expert": 4},
        "banner": "the REAL tiny_moe train step on a data=2 x expert=4 "
                  "mesh (PR 7 ledger fixture: tuple-form all-to-all "
                  "dispatch)",
    },
    "zero3_bucketed_async_step": {
        "spec": dict(model="tiny", num_layers=2, max_seq_len=64),
        "zero": dict(_FORCING, stage=3),
        "asyncify": True,
        "banner": "the BUCKETED zero3 tiny train step (overlap_comm, "
                  "reduce_bucket_size=4096 elements, "
                  "stage3_prefetch_bucket_size=8192 -> 2 layer chunks + "
                  "mid-backward grad-sync points), asyncified",
    },
    "zero2_exact_bucketed_step": {
        "spec": dict(model="tiny", hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=64, vocab_size=512),
        "zero": dict(_FORCING, stage=2),
        "batch": dict(train_batch_size=32,
                      train_micro_batch_size_per_gpu=2,
                      gradient_accumulation_steps=2),
        "banner": "the EXACT-wire bucketed zero2 tiny train step — the "
                  "SAME config as zero2_qgz_bucketed_async_step minus "
                  "the quantized-wire flags; the unquantized baseline "
                  "the wire-byte-reduction contract divides against",
    },
    "zero3_qwz_update_defer_async_step": {
        "spec": dict(model="tiny", num_layers=2, max_seq_len=64),
        "zero": dict(_FORCING, stage=3, zero_quantized_weights=True,
                     update_bucket_size=4096),
        "asyncify": True,
        "banner": "the BUCKETED-UPDATE double-buffered zero3 qwZ train "
                  "step (overlap_step: per-bucket fenced weight update, "
                  "deferred zero_param_update publish gather feeding "
                  "the next forward's double buffer), asyncified",
    },
    "zero2_qgz_bucketed_async_step": {
        "spec": dict(model="tiny", hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=64, vocab_size=512),
        "zero": dict(_FORCING, stage=2, zero_quantized_gradients=True,
                     loco_error_feedback=True),
        "batch": dict(train_batch_size=32,
                      train_micro_batch_size_per_gpu=2,
                      gradient_accumulation_steps=2),
        "asyncify": True,
        "banner": "the COMPOSED bucketed-quantized zero2 tiny train "
                  "step (zero_quantized_gradients + loco_error_feedback "
                  "+ overlap_comm -> fenced int8 qgZ buckets, 2 layer "
                  "chunks), asyncified",
    },
}

_SEQ_LEN = 32   # the lowered token shape every fixture pins


def _generate_one(stem: str, out_dir: str) -> str:
    """Child-mode body: runs under PINNED_ENV in a fresh process."""
    import jax

    import deepspeed_tpu as dst
    from deepspeed_tpu.profiling.observatory.hlo import (
        asyncify_hlo,
        iter_collective_lines,
    )
    from deepspeed_tpu.profiling.observatory.ledger import ledger_for_engine

    fx = FIXTURE_SPECS[stem]
    spec_kwargs = dict(fx["spec"])
    model = spec_kwargs.pop("model")
    spec = dst.causal_lm_spec(model, dtype="float32", **spec_kwargs)
    config = {
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "zero_optimization": dict(fx["zero"]),
        "steps_per_print": 10 ** 9,
    }
    config.update(fx.get("batch") or {})
    if fx.get("mesh"):
        config["mesh"] = dict(fx["mesh"])
    engine, *_ = dst.initialize(model=spec, config=config)
    ledger, mem = ledger_for_engine(engine, fold=False, seq_len=_SEQ_LEN)
    full_text = ledger.hlo_text
    lines = full_text.splitlines()
    # the module header block: line 0 plus any header continuation that
    # carries the entry's donation directives / parameter layout —
    # memlint's text tier reads input_output_alias + the entry layout
    # from the committed fixture, so these lines are load-bearing
    header = "\n".join(dict.fromkeys(
        ln for i, ln in enumerate(lines)
        if i == 0 or ln.startswith("HloModule")
        or "input_output_alias=" in ln
        or "entry_computation_layout=" in ln))
    body = "\n".join(iter_collective_lines(full_text))
    # live memory observations for --write-memory-contracts: the parent
    # process (no jax backend) bootstraps the memlint sidecar contracts
    # from the committed fixture TEXT plus these generation-time numbers
    from deepspeed_tpu.autotuning.memory_model import (
        predicted_state_bytes_per_device,
    )

    memobs = {
        "memory_analysis": mem,
        "predicted_state_bytes": predicted_state_bytes_per_device(engine),
        "donated_params": len(jax.tree.leaves(engine.state)),
        "expect_donation": not getattr(engine, "_offload_param_stream",
                                       False),
        "zero_stage": engine.zero_stage,
        "world": engine.dp_world_size,
    }
    print("MEMOBS " + json.dumps(memobs, sort_keys=True))
    if fx.get("asyncify"):
        body = asyncify_hlo(body)
    banner_lines = [
        "// --- trimmed fixture: module header + every collective-bearing",
        f"// --- line of {fx['banner']},",
        "// --- regenerated by tools/regen_hlo_fixtures.py under",
        "// --- JAX_PLATFORMS=cpu,",
        "// --- XLA_FLAGS=--xla_force_host_platform_device_count=8"
        + ("," if fx.get("asyncify") else "."),
    ]
    if fx.get("asyncify"):
        banner_lines.append(
            "// --- then passed through hlo.asyncify_hlo (the surface "
            "transform")
        banner_lines.append(
            "// --- XLA's async-collective-creator pass applies on "
            "TPU/GPU).")
    out_path = os.path.join(out_dir, stem + ".hlo.txt")
    with open(out_path, "w") as f:
        f.write(header + "\n\n" + "\n".join(banner_lines) + "\n\n"
                + body + "\n")
    return out_path


def _regen_contract(stem: str, hlo_path: str, contracts_out: str,
                    allow_loosen: bool) -> None:
    from deepspeed_tpu.analysis.hlolint import (
        LintConfig,
        bootstrap_contract,
        contracts_dir,
        load_contract,
        write_contract,
    )
    from deepspeed_tpu.profiling.observatory.ledger import build_ledger

    committed = os.path.join(contracts_dir(), stem + ".json")
    if os.path.exists(committed):
        # keep the committed config block — it IS the pinned lint config
        cfg = LintConfig.from_contract(load_contract(committed),
                                       program=stem)
    else:
        fx = FIXTURE_SPECS[stem]
        z = fx["zero"]
        quant_w = bool(z.get("zero_quantized_weights"))
        quant_g = bool(z.get("zero_quantized_gradients"))
        wire = "exact"
        if quant_w or quant_g:
            wire = "qz+loco" if (quant_g and z.get("loco_error_feedback")) \
                else "qz"
        cfg = LintConfig(program=stem, world=8,
                         zero_stage=z["stage"],
                         wire_format=wire, quant_weights=quant_w,
                         quant_grads=quant_g,
                         expect_async=bool(fx.get("asyncify")))
    with open(hlo_path) as f:
        text = f.read()
    ledger = build_ledger(text, program=stem, world=cfg.world,
                          zero_stage=cfg.zero_stage)
    if cfg.planned_grad_sync_collectives is not None:
        # re-pin the fence-defeat floor at what the regenerated program
        # actually shows (the plan changed with the program)
        cfg.planned_grad_sync_collectives = sum(
            1 for op in ledger.ops if op.subsystem == "zero_grad_sync")
    doc = bootstrap_contract(ledger, cfg, hlo_name=stem + ".hlo.txt")
    out = os.path.join(contracts_out, stem + ".json")
    write_contract(out, doc, allow_loosen=allow_loosen)
    print(f"regen: contract {out}")


def _regen_memory_contract(stem: str, hlo_path: str, memobs: dict,
                           contracts_out: str,
                           allow_loosen: bool) -> None:
    """Bootstrap/retighten the memlint SIDECAR contract for one fixture:
    text-tier bounds from the committed fixture's entry header, live-tier
    bounds (peak/temp) from the generation subprocess's
    ``memory_analysis`` numbers, the predicted state pinned into the
    config block so ``--fixtures`` can enforce the residency ceiling
    with no engine."""
    from deepspeed_tpu.analysis.memlint import (
        MemLintConfig,
        bootstrap_contract as mem_bootstrap,
        observe_hlo,
        write_contract as mem_write,
    )
    from deepspeed_tpu.autotuning.memory_model import peak_bytes_from_stats

    with open(hlo_path) as f:
        obs = observe_hlo(f.read())
    mem = memobs.get("memory_analysis") or None
    if mem:
        obs.temp_bytes = mem.get("temp_size_in_bytes")
        obs.alias_size_bytes = mem.get("alias_size_in_bytes")
        obs.peak_bytes = peak_bytes_from_stats(mem)
    obs.predicted_state_bytes = memobs.get("predicted_state_bytes")
    cfg = MemLintConfig(
        program=stem, world=int(memobs.get("world") or 8),
        zero_stage=int(memobs.get("zero_stage") or 0),
        expect_donation=bool(memobs.get("expect_donation", True)),
        donated_params=memobs.get("donated_params"))
    doc = mem_bootstrap(obs, cfg, hlo_name=stem + ".hlo.txt")
    out = os.path.join(contracts_out, stem + ".json")
    mem_write(out, doc, allow_loosen=allow_loosen)
    print(f"regen: memory contract {out}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="regen_hlo_fixtures",
        description="regenerate the committed observatory HLO fixtures "
                    "(and optionally their hlolint contracts) from "
                    "their pinned configs")
    p.add_argument("--out", default=FIXTURES_DIR,
                   help="fixture output dir (default: the committed "
                        "tests/unit/observatory_fixtures)")
    p.add_argument("--only", action="append", default=None,
                   metavar="STEM", help="regenerate just these fixtures")
    p.add_argument("--list", action="store_true",
                   help="print the fixture stems + pinned configs")
    p.add_argument("--write-contracts", action="store_true",
                   help="also rebootstrap each fixture's hlolint "
                        "contract (shrink-only unless --allow-loosen)")
    p.add_argument("--write-memory-contracts", action="store_true",
                   help="also rebootstrap each fixture's memlint "
                        "SIDECAR memory contract from the fixture "
                        "header + the generation subprocess's live "
                        "memory_analysis numbers (shrink-only unless "
                        "--allow-loosen)")
    p.add_argument("--contracts-out", default=None,
                   help="contract output dir (default: the committed "
                        "analysis/hlolint/contracts)")
    p.add_argument("--memory-contracts-out", default=None,
                   help="memory contract output dir (default: the "
                        "committed analysis/memlint/contracts)")
    p.add_argument("--allow-loosen", action="store_true",
                   help="permit contract regeneration to LOOSEN "
                        "committed bounds (deliberate program changes)")
    p.add_argument("--_generate", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args._generate:
        print(_generate_one(args._generate, args.out))
        return 0

    stems = list(FIXTURE_SPECS)
    if args.only:
        unknown = set(args.only) - set(stems)
        if unknown:
            print(f"regen: unknown fixture(s) {sorted(unknown)} "
                  f"(known: {stems})", file=sys.stderr)
            return 2
        stems = [s for s in stems if s in args.only]
    if args.list:
        for stem in stems:
            fx = FIXTURE_SPECS[stem]
            print(f"{stem}: zero={json.dumps(fx['zero'], sort_keys=True)}"
                  + (f" mesh={fx['mesh']}" if fx.get("mesh") else "")
                  + (" [asyncified]" if fx.get("asyncify") else ""))
        return 0

    os.makedirs(args.out, exist_ok=True)
    contracts_out = args.contracts_out
    if contracts_out is None:
        from deepspeed_tpu.analysis.hlolint import contracts_dir

        contracts_out = contracts_dir()
    os.makedirs(contracts_out, exist_ok=True)
    mem_contracts_out = args.memory_contracts_out
    if mem_contracts_out is None:
        from deepspeed_tpu.analysis.memlint import (
            contracts_dir as mem_contracts_dir,
        )

        mem_contracts_out = mem_contracts_dir()
    if args.write_memory_contracts:
        os.makedirs(mem_contracts_out, exist_ok=True)
    failures = 0
    for stem in stems:
        env = dict(os.environ, **PINNED_ENV)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--_generate", stem, "--out", args.out],
            env=env, capture_output=True, text=True, cwd=REPO_ROOT)
        if proc.returncode != 0:
            failures += 1
            print(f"regen: {stem} FAILED:\n{proc.stderr[-2000:]}",
                  file=sys.stderr)
            continue
        hlo_path = proc.stdout.strip().splitlines()[-1]
        memobs = {}
        for line in proc.stdout.splitlines():
            if line.startswith("MEMOBS "):
                memobs = json.loads(line[len("MEMOBS "):])
        print(f"regen: {hlo_path}")
        if args.write_contracts:
            try:
                _regen_contract(stem, hlo_path, contracts_out,
                                args.allow_loosen)
            except Exception as e:
                failures += 1
                print(f"regen: contract for {stem} FAILED: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
        if args.write_memory_contracts:
            try:
                _regen_memory_contract(stem, hlo_path, memobs,
                                       mem_contracts_out,
                                       args.allow_loosen)
            except Exception as e:
                failures += 1
                print(f"regen: memory contract for {stem} FAILED: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
