#!/usr/bin/env python
"""Assemble a real-text corpus from documentation shipped in this image.

The convergence lane (round-4 verdict Missing #3) needs REAL natural-language
text — the reference's nightly model suites train on real corpora
(/root/reference/tests/model/). This image has no network egress, so the
corpus is the English prose already on disk: package documentation, READMEs,
and licenses from /usr/share/doc, /usr/share/common-licenses, and
site-packages *.md/*.rst/README files. Paragraph-level dedup keeps the
boilerplate (identical license texts repeated per package) from dominating.

Deterministic: sources sorted, content hashed; output committed at
data/real_text_corpus.txt so the lane is reproducible without rebuilding.
"""
import glob
import hashlib
import os
import sys

OUT = os.path.join(os.path.dirname(__file__), os.pardir, "data",
                   "real_text_corpus.txt")
TARGET_BYTES = 4_000_000


def _sources():
    # READMEs/guides first (varied technical prose); license texts last and
    # per-package "copyright" files excluded — their thousands of lightly
    # edited license variants would otherwise dominate the token budget
    pats = [
        "/opt/venv/lib/python3*/site-packages/**/*.md",
        "/opt/venv/lib/python3*/site-packages/**/*.rst",
        "/usr/share/doc/**/README*",
        "/usr/share/doc/**/*",
        "/usr/share/common-licenses/*",
    ]
    seen, seen_set = [], set()
    for pat in pats:
        for p in sorted(glob.glob(pat, recursive=True)):
            if (os.path.isfile(p) and p not in seen_set
                    and not p.endswith((".gz", ".png", ".svg"))
                    and os.path.basename(p) != "copyright"):
                seen.append(p)
                seen_set.add(p)
    return seen


def _prose_paragraphs(text: str):
    """Split into paragraphs, keep ones that look like English prose."""
    for para in text.split("\n\n"):
        para = para.strip()
        if len(para) < 120:              # headers, stubs
            continue
        if sum(c.isascii() for c in para) < 0.99 * len(para):
            continue
        letters = sum(c.isalpha() or c.isspace() for c in para)
        if letters < 0.8 * len(para):    # tables, code, hex blobs
            continue
        yield para


def _docstring_paragraphs():
    """English prose from library docstrings (numpy/scipy/sklearn/jax docs
    are reference-manual-quality text, megabytes of it)."""
    import ast

    roots = []
    for pkg in ("numpy", "scipy", "sklearn", "jax", "pandas",
                "matplotlib", "torch", "flax"):
        roots += sorted(glob.glob(
            f"/opt/venv/lib/python3*/site-packages/{pkg}/**/*.py",
            recursive=True))
    for path in roots:
        try:
            with open(path, "r", encoding="utf-8", errors="ignore") as f:
                tree = ast.parse(f.read(1 << 20))
        except (OSError, SyntaxError, ValueError):
            continue
        for node in ast.walk(tree):
            if isinstance(node, (ast.Module, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                doc = ast.get_docstring(node)
                if doc:
                    yield from _prose_paragraphs(doc)


def build(target=TARGET_BYTES):
    seen_hashes = set()
    chunks = []
    total = 0

    def _add(para) -> bool:
        nonlocal total
        h = hashlib.sha1(para.encode()).digest()
        if h in seen_hashes:
            return False
        seen_hashes.add(h)
        chunks.append(para)
        total += len(para) + 2
        return total >= target

    done = False
    for path in _sources():
        try:
            with open(path, "r", encoding="utf-8", errors="ignore") as f:
                text = f.read(1 << 20)
        except OSError:
            continue
        for para in _prose_paragraphs(text):
            if _add(para):
                done = True
                break
        if done:
            break
    if not done:
        for para in _docstring_paragraphs():
            if _add(para):
                break
    return "\n\n".join(chunks)


if __name__ == "__main__":
    corpus = build()
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        f.write(corpus)
    print(f"wrote {len(corpus)/1e6:.2f} MB, "
          f"sha1 {hashlib.sha1(corpus.encode()).hexdigest()[:12]}",
          file=sys.stderr)
