#!/usr/bin/env python
"""Real-data convergence lane (round-4 verdict Missing #3).

The reference ships nightly MODEL convergence suites on real corpora
(/root/reference/tests/model/, SURVEY.md §4) — evidence that training
DECREASES HELD-OUT loss on real text, not just that synthetic tokens can be
memorized. This lane trains a GPT-2-125M-body byte-level LM (vocab 256 —
no network egress, so no pretrained BPE; byte-level keeps the data real
and the tokenizer dependency-free) on ``data/real_text_corpus.txt`` (4 MB
of deduplicated English prose shipped in the image, tools/build_corpus.py)
with a 5% held-out tail, evaluating held-out cross-entropy every eval
window ON CHIP.

Pass criteria (committed with the artifact):
  * every loss finite;
  * held-out CE strictly decreases from first to last eval;
  * final held-out CE below 2.6 nats/byte (random = ln(256) ≈ 5.55;
    a few MB and ~20 min of chip time land well under 2.6 — the committed
    CONVERGE_r05.json band is the reproduction target).

Usage: python tools/converge_lane.py [out.json]
Env: CONVERGE_STEPS (default 1000), CONVERGE_EVAL_EVERY (100).
     CONVERGE_WIRE=exact|qgz (default exact): ``qgz`` runs the COMPOSED
     quantized-wire lane — ZeRO-2 + qgZ int8 gradient reduce + LoCo error
     feedback under the bucketed overlap scheduler (ISSUE 10) — against
     the SAME pass criteria, so wire compression proves convergence
     parity on real text, not just synthetic-loss closeness. The lane
     name is recorded in the artifact (``wire`` field).
"""
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEQ = 512
BATCH = 32
HELDOUT_FRAC = 0.05
PASS_CE = 2.6


def batches(tokens: np.ndarray, rng: np.random.Generator, n: int):
    """n random [BATCH, SEQ] windows from a token stream."""
    for _ in range(n):
        starts = rng.integers(0, len(tokens) - SEQ - 1, BATCH)
        yield np.stack([tokens[s:s + SEQ] for s in starts]).astype(np.int32)


def main(out_path: str) -> int:
    import deepspeed_tpu as dst

    steps = int(os.environ.get("CONVERGE_STEPS", 1000))
    eval_every = int(os.environ.get("CONVERGE_EVAL_EVERY", 100))
    eval_every = max(1, min(eval_every, steps))   # smoke runs: >= 1 window
    wire = os.environ.get("CONVERGE_WIRE", "exact").lower()
    if wire not in ("exact", "qgz"):
        print(f"CONVERGE_WIRE must be exact|qgz, got {wire!r}",
              file=sys.stderr)
        return 2

    raw = open(os.path.join(REPO, "data", "real_text_corpus.txt"), "rb").read()
    toks = np.frombuffer(raw, np.uint8)
    split = int(len(toks) * (1 - HELDOUT_FRAC))
    train, held = toks[:split], toks[split:]

    spec = dst.causal_lm_spec("gpt2_125m", vocab_size=256, max_seq_len=SEQ,
                              remat="full", attention="flash")
    config = {
        "train_batch_size": BATCH,
        "train_micro_batch_size_per_gpu": BATCH,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
        "scheduler": {"type": "WarmupCosineLR",
                      "params": {"warmup_num_steps": 50,
                                 "total_num_steps": steps}},
        "zero_optimization": (
            # the composed quantized-wire lane: qgZ int8 gradient reduce +
            # LoCo residuals, bucketed/chunked by the overlap scheduler —
            # same pass band as the exact lane (wire parity ON REAL TEXT)
            {"stage": 2, "zero_quantized_gradients": True,
             "loco_error_feedback": True, "overlap_comm": True}
            if wire == "qgz" else {"stage": 1}),
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 9,
    }
    engine, *_ = dst.initialize(model=spec, config=config)
    if wire == "qgz" and engine._compressed is None:
        # a lane LABELED qgz must not silently measure exact collectives
        # (the engine falls back at dp world 1) — refuse instead
        print("CONVERGE_WIRE=qgz needs data-parallel width > 1 (the "
              "engine fell back to exact collectives); run on a mesh or "
              "with forced host devices", file=sys.stderr)
        return 2

    rng = np.random.default_rng(0)
    ev_rng = np.random.default_rng(1)
    eval_set = list(batches(held, ev_rng, 4))     # fixed held-out batches

    def heldout_ce() -> float:
        return float(np.mean([float(engine.eval_batch(b))
                              for b in eval_set]))

    t0 = time.time()
    train_curve, held_curve = [], []
    for w in range(steps // eval_every):
        loss = engine.train_batches(
            iter(batches(train, rng, eval_every)), eval_every)
        train_curve.append(round(float(loss), 4))
        held_curve.append(round(heldout_ce(), 4))
        print(f"[converge] step {(w + 1) * eval_every}: "
              f"train {train_curve[-1]} held-out {held_curve[-1]}",
              file=sys.stderr)

    finite = bool(np.isfinite(train_curve + held_curve).all())
    out = {
        "corpus": "data/real_text_corpus.txt (4MB deduplicated English "
                  "prose from image docs; tools/build_corpus.py)",
        "model": "gpt2_125m body, byte-level vocab 256 "
                 f"({spec.num_params / 1e6:.0f}M params)",
        "wire": wire,
        "steps": steps, "batch": BATCH, "seq": SEQ,
        "tokens_seen": steps * BATCH * SEQ,
        "train_curve": train_curve,
        "heldout_ce_curve": held_curve,
        "random_ce": round(float(np.log(256)), 4),
        "final_heldout_ce": held_curve[-1],
        "finite": finite,
        "heldout_decreasing": held_curve[-1] < held_curve[0],
        "passed": finite and held_curve[-1] < held_curve[0]
        and held_curve[-1] < PASS_CE,
        "wall_s": round(time.time() - t0, 1),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("final_heldout_ce", "heldout_decreasing", "passed",
                       "tokens_seen", "wall_s")}))
    return 0 if out["passed"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  os.path.join(REPO, "CONVERGE_r05.json")))
